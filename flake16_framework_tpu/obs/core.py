"""Process-wide telemetry: spans, counters/gauges, JSONL sink, manifest,
heartbeat.

Disabled by default and ZERO-overhead when off: the env switch is
``F16_TELEMETRY`` (unset/empty = off; ``1`` = on at the default root
``_scratch/telemetry`` under the CWD; any other value = the root
directory). Every public entry point's first action is a single
``_state is None`` check, and ``span()`` returns one shared no-op object,
so instrumented hot loops stay within noise of the uninstrumented code
(test_obs.py pins the disabled-path cost; the bench's per-config walls are
the production check).

When on, one run = one directory ``<root>/run-<token>/`` holding
``events.jsonl`` (schema.EVENT_FIELDS; atomic appends — O_APPEND +
single-write, safe under concurrent threads and processes) and
``manifest.json`` (schema.MANIFEST_FIELDS; enriched in place as facts
become known — jax only reports its backend once it is imported and up).
A daemon heartbeat thread stamps liveness every
``F16_TELEMETRY_HEARTBEAT_S`` (default 60 s, 0 disables), so a
multi-hour grid run that dies leaves a diagnosable trail
(PROFILE.md: the round-5 grid ran 8.3 h with no such trail).

The ``scores profile=DIR`` jax.profiler hook is the ``profiler_trace``
backend of this same subsystem: it wraps the trace and stamps a
``profile`` event, telemetry-enabled or not (an explicit profile request
must not silently depend on F16_TELEMETRY).
"""

import json
import os
import random
import subprocess
import sys
import threading
import time

from flake16_framework_tpu.obs import schema

_lock = threading.Lock()
_state = None  # _RunState when enabled; module-level None = the fast path
_run_seq = 0   # disambiguates same-second reconfigures within one process
_flight = None  # obs.flight.FlightRecorder when F16_FLIGHT armed
_xprof_done = set()  # tags already captured (one xprof per process+tag)


class _NullSpan:
    """The shared no-op span (disabled path): one allocation per process."""

    __slots__ = ()
    wall_s = 0.0
    cold = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add(self, **fields):
        return self


_NULL_SPAN = _NullSpan()


class _RunState:
    __slots__ = ("run", "dir", "fd", "t0", "counters", "gauges", "seen",
                 "hb_stop", "hb_thread")

    def __init__(self, run, run_dir, fd):
        self.run = run
        self.dir = run_dir
        self.fd = fd
        self.t0 = time.time()
        self.counters = {}
        self.gauges = {}  # name -> last emitted value (manifest flush)
        self.seen = set()  # (span name, key) pairs already timed once
        self.hb_stop = None
        self.hb_thread = None


# -- sink ---------------------------------------------------------------


def append_jsonl(path, obj):
    """Atomically append one JSON object line to ``path``.

    O_APPEND + a single write(2): concurrent writers (threads or
    processes) interleave whole lines, never fragments. Shared with
    bench.py's stage ledger so the crash-evidence record and the
    telemetry sink cannot diverge on append semantics."""
    line = (json.dumps(obj) + "\n").encode()
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, line)
    finally:
        os.close(fd)


def _emit(state, obj):
    obj.setdefault("ts", round(time.time(), 4))
    obj.setdefault("run", state.run)
    line = (json.dumps(obj) + "\n").encode()
    with _lock:
        os.write(state.fd, line)
    flt = _flight
    if flt is not None:  # mirror into the crash-surviving ring
        try:
            flt.record(obj)
        except (OSError, ValueError):
            pass


# -- lifecycle ----------------------------------------------------------


def enabled():
    return _state is not None


def current_run_dir():
    """The active run directory, or None when telemetry is off."""
    return _state.dir if _state is not None else None


def default_root():
    raw = os.environ.get("F16_TELEMETRY", "")
    if raw and raw != "1":
        return raw
    return os.path.join(os.getcwd(), "_scratch", "telemetry")


def configure(root=None, heartbeat_s=None):
    """Enable telemetry into ``<root>/run-<token>/`` (idempotent per
    process: reconfiguring shuts the previous run down first). Called
    automatically at import when ``F16_TELEMETRY`` is set; tests and
    drivers may call it explicitly."""
    global _state, _run_seq
    shutdown()
    root = root or default_root()
    run = time.strftime("%Y%m%d-%H%M%S") + f"-{os.getpid()}"
    with _lock:
        _run_seq += 1
        if _run_seq > 1:  # same second + same pid must not share a dir
            run += f".{_run_seq}"
    run_dir = os.path.join(root, f"run-{run}")
    os.makedirs(run_dir, exist_ok=True)
    fd = os.open(os.path.join(run_dir, schema.EVENTS_FILE),
                 os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    _state = _RunState(run, run_dir, fd)
    _write_manifest_base(_state)
    _arm_flight(run_dir)
    if heartbeat_s is None:
        heartbeat_s = float(os.environ.get("F16_TELEMETRY_HEARTBEAT_S",
                                           "60") or 0)
    if heartbeat_s > 0:
        start_heartbeat(heartbeat_s)
    return run_dir


def shutdown():
    """Stop the heartbeat, close the sink, return to the disabled state.
    Final manifest facts (compilation-cache directory and traffic) are
    stamped first, while the sink is still up."""
    global _state, _flight
    if _state is not None:
        _finalize_manifest()
    state, _state = _state, None
    flt, _flight = _flight, None
    if flt is not None:
        flt.close()
    if state is None:
        return
    stop_heartbeat(state)
    with _lock:
        os.close(state.fd)


def _arm_flight(run_dir):
    """Arm the crash-surviving flight ring when F16_FLIGHT is set (off by
    default, same contract as the sink). Once armed, ``_emit`` mirrors
    every event into the ring."""
    global _flight
    from flake16_framework_tpu.obs import flight as _flightmod

    path = _flightmod.env_path(run_dir=run_dir)
    if not path:
        return
    try:
        _flight = _flightmod.FlightRecorder(path)
    except OSError:
        _flight = None
        return
    event("flight", action="armed", path=str(path),
          capacity=_flight.capacity)


def _finalize_manifest():
    """Merge cache facts into the manifest: where the persistent XLA
    compilation cache lives and how often this process hit/missed it (the
    round-3 suite budget leans on that cache — make it visible per run).
    Called at shutdown AND on every heartbeat (a killed long-running
    serving process must not lose its hit/miss aggregates to atexit never
    firing). Reads jax and obs.aot via sys.modules only: telemetry never
    initializes either."""
    fields = {}
    cache_dir = None
    jaxmod = sys.modules.get("jax")
    if jaxmod is not None:
        try:
            cache_dir = jaxmod.config.jax_compilation_cache_dir
        except Exception:
            cache_dir = None
    if not cache_dir:
        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir:
        fields["jax_cache_dir"] = str(cache_dir)
    aot = sys.modules.get("flake16_framework_tpu.obs.aot")
    if aot is not None:
        try:
            stats = aot.cache_stats()
            fields["jax_cache_hits"] = int(stats.get("hits", 0))
            fields["jax_cache_misses"] = int(stats.get("misses", 0))
        except Exception:
            pass
    state = _state
    if state is not None and state.gauges:
        # Gauge last-values ride the same flush (heartbeat + shutdown +
        # flight dump): a SIGKILL'd serve keeps its final queue-depth/p99.
        fields["gauges"] = dict(state.gauges)
    if fields:
        manifest_update(**fields)


def _maybe_configure_from_env():
    if os.environ.get("F16_TELEMETRY"):
        configure()


# -- spans --------------------------------------------------------------


class Span:
    """Timed region. ``cold`` is True on the first occurrence of
    (name, key) in this process — on jitted paths that call carries
    trace+compile wall, so cold-vs-warm is the compile/execute split the
    report renders. ``key`` should name the compilation unit (e.g. the
    model family), not the config: one compile serves all configs of a
    family."""

    __slots__ = ("_state", "name", "key", "fields", "t0", "wall_s", "cold")

    def __init__(self, state, name, key, fields):
        self._state = state
        self.name = name
        self.key = key
        self.fields = fields
        self.wall_s = 0.0
        self.cold = False

    def add(self, **fields):
        self.fields.update(fields)
        return self

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.wall_s = time.time() - self.t0
        state = self._state
        seen_key = (self.name, self.key)
        with _lock:
            self.cold = seen_key not in state.seen
            state.seen.add(seen_key)
        ev = {"kind": "span", "name": self.name,
              "wall_s": round(self.wall_s, 6), "cold": self.cold,
              "tid": threading.get_ident()}
        if exc_type is not None:
            ev["error"] = exc_type.__name__
        ev.update(self.fields)
        _emit(state, ev)
        return False


def span(name, key=None, **fields):
    """``with obs.span("scores.fit", key=family): ...`` — no-op when off."""
    state = _state
    if state is None:
        return _NULL_SPAN
    return Span(state, name, key, fields)


# -- counters and gauges ------------------------------------------------


def counter_add(name, inc=1, **fields):
    """Add to a monotonic counter and emit the post-increment total."""
    state = _state
    if state is None:
        return
    with _lock:
        total = state.counters.get(name, 0) + inc
        state.counters[name] = total
    _emit(state, {"kind": "counter", "name": name, "inc": inc,
                  "total": total, **fields})


def gauge(name, value, **fields):
    state = _state
    if state is None or value is None:
        return
    value = round(float(value), 4)
    with _lock:  # last-value, flushed into the manifest; dict writes
        state.gauges[name] = value  # race from serve worker threads
    _emit(state, {"kind": "gauge", "name": name, "value": value, **fields})


def event(kind, **fields):
    """Emit a raw event of a schema-known kind (bench stage mirroring)."""
    state = _state
    if state is None:
        return
    _emit(state, {"kind": kind, **fields})


def host_rss_peak_mb():
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024


def device_memory_peak_mb():
    """Peak device memory over local devices via ``device.memory_stats()``,
    None where the backend doesn't report it (CPU). Never imports jax
    itself — telemetry must not initialize a backend."""
    jaxmod = sys.modules.get("jax")
    if jaxmod is None:
        return None
    peak = None
    try:
        for d in jaxmod.devices():
            stats = d.memory_stats()
            if not stats:
                continue
            b = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
            if b is not None:
                peak = max(peak or 0, b)
    except Exception:
        return None
    return None if peak is None else peak / 1e6


def emit_memory_gauges():
    """Stamp the standard memory gauges (host RSS peak; device peak where
    the backend reports one)."""
    if _state is None:
        return
    gauge("host_rss_peak_mb", host_rss_peak_mb())
    gauge("device_mem_peak_mb", device_memory_peak_mb())


# -- per-request trace context ------------------------------------------


def mint_trace(parent=None):
    """Trace context for one request: ``{trace_id, span_id[, parent_id]}``
    or None when telemetry is off or the request loses the
    ``F16_TRACE_SAMPLE`` coin flip (default 1.0 = every request; 0
    disables). Minted at ``serve.submit()`` and propagated
    queue→batcher→dispatch→response; the batcher records batch fan-in as
    span links and stamps per-request lanes the trace renderer draws next
    to the per-thread lanes."""
    if _state is None:
        return None
    try:
        rate = float(os.environ.get("F16_TRACE_SAMPLE", "1") or 0.0)
    except ValueError:
        rate = 0.0
    if rate <= 0.0 or (rate < 1.0 and random.random() >= rate):
        return None
    ctx = {"trace_id": os.urandom(8).hex(), "span_id": os.urandom(4).hex()}
    if parent:
        ctx["parent_id"] = parent.get("span_id")
        ctx["trace_id"] = parent.get("trace_id", ctx["trace_id"])
    return ctx


def adopt_trace(parent):
    """Adopt a trace context minted in ANOTHER process (ISSUE 19): a
    fleet worker receiving ``trace_id``/``parent_id`` wire fields joins
    the router's trace with a fresh local span id and NO second
    ``F16_TRACE_SAMPLE`` coin flip — the router already made the
    sampling decision, and re-flipping here would tear sampled requests
    apart mid-trace. Returns None when ``parent`` is falsy (the request
    was never sampled) or telemetry is off in this process."""
    if _state is None or not parent:
        return None
    tid = parent.get("trace_id")
    if not tid:
        return None
    ctx = {"trace_id": tid, "span_id": os.urandom(4).hex()}
    pid = parent.get("parent_id") or parent.get("span_id")
    if pid:
        ctx["parent_id"] = pid
    return ctx


# -- manifest -----------------------------------------------------------


def _git_sha():
    try:
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        r = subprocess.run(["git", "rev-parse", "HEAD"], cwd=repo,
                           capture_output=True, text=True, timeout=10)
        return r.stdout.strip() or None
    except Exception:
        return None


def _env_fingerprint():
    prefixes = ("F16_", "BENCH_", "GRID_", "JAX_", "XLA_")
    return {k: v for k, v in sorted(os.environ.items())
            if k.startswith(prefixes)}


def _write_manifest_base(state):
    manifest = {
        "schema": schema.MANIFEST_SCHEMA,
        "run": state.run,
        "started_ts": round(state.t0, 4),
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "hostname": os.uname().nodename,
        "pid": os.getpid(),
        "git_sha": _git_sha(),
        "env": _env_fingerprint(),
    }
    _dump_manifest(state, manifest)


def _dump_manifest(state, manifest):
    from flake16_framework_tpu.utils.atomic import atomic_write

    path = os.path.join(state.dir, schema.MANIFEST_FILE)
    with atomic_write(path, "w") as fd:
        json.dump(manifest, fd, indent=1, default=str)


def manifest_update(**fields):
    """Merge facts into manifest.json (atomic read-modify-replace)."""
    state = _state
    if state is None:
        return
    path = os.path.join(state.dir, schema.MANIFEST_FILE)
    with _lock:
        try:
            with open(path) as fd:
                manifest = json.load(fd)
        except (OSError, ValueError):
            manifest = {"schema": schema.MANIFEST_SCHEMA, "run": state.run,
                        "started_ts": round(state.t0, 4),
                        "argv": list(sys.argv),
                        "python": sys.version.split()[0],
                        "env": _env_fingerprint()}
        manifest.update(fields)
        _dump_manifest(state, manifest)


def record_jax_manifest(mesh=None):
    """Enrich the manifest with the device facts only jax knows — version,
    backend, device kind/count, mesh shape. Cheap no-op when off; safe to
    call before/without jax (fields are simply absent)."""
    if _state is None:
        return
    jaxmod = sys.modules.get("jax")
    if jaxmod is None:
        return
    try:
        devices = jaxmod.devices()
        fields = {
            "jax_version": jaxmod.__version__,
            "backend": jaxmod.default_backend(),
            "device_kind": devices[0].device_kind if devices else None,
            "device_count": len(devices),
        }
    except Exception:
        return
    if mesh is not None:
        fields["mesh_shape"] = {str(k): int(v)
                                for k, v in dict(mesh.shape).items()}
    manifest_update(**fields)


# -- heartbeat ----------------------------------------------------------


def start_heartbeat(interval_s=60.0):
    """Start (or restart) the liveness thread: one ``heartbeat`` event per
    interval with uptime, peak RSS, device memory, and the counter
    snapshot. Daemon — never blocks process exit."""
    state = _state
    if state is None:
        return
    stop_heartbeat(state)
    stop = threading.Event()

    def beat():
        while not stop.wait(interval_s):
            st = _state
            if st is not state:
                return
            with _lock:
                counters = dict(state.counters)
            ev = {"kind": "heartbeat",
                  "uptime_s": round(time.time() - state.t0, 1),
                  "rss_mb": host_rss_peak_mb(), "counters": counters}
            dev = device_memory_peak_mb()
            if dev is not None:
                ev["device_mem_mb"] = round(dev, 1)
            _emit(state, ev)
            # Flush manifest aggregates on the same cadence: a killed
            # long-running process (serving) must not lose its cache
            # hit/miss facts to atexit never firing.
            try:
                _finalize_manifest()
            except Exception:
                pass

    t = threading.Thread(target=beat, name="f16-telemetry-heartbeat",
                         daemon=True)
    state.hb_stop, state.hb_thread = stop, t
    t.start()


def stop_heartbeat(state=None):
    state = state if state is not None else _state
    if state is None or state.hb_stop is None:
        return
    state.hb_stop.set()
    state.hb_thread.join(timeout=5)
    state.hb_stop = state.hb_thread = None


# -- profiler backend ---------------------------------------------------


class profiler_trace:
    """Context manager: ``jax.profiler.trace(trace_dir)`` + a ``profile``
    event. ``trace_dir=None`` is a no-op — callers pass their optional
    profile knob straight through. Works with telemetry off (an explicit
    profile request stands on its own); the event is emitted only when the
    sink is up."""

    def __init__(self, trace_dir):
        self.trace_dir = trace_dir
        self._cm = None

    def __enter__(self):
        if self.trace_dir is not None:
            import jax

            self._cm = jax.profiler.trace(self.trace_dir)
            self._cm.__enter__()
            event("profile", trace_dir=str(self.trace_dir))
        return self

    def __exit__(self, *exc):
        if self._cm is not None:
            return self._cm.__exit__(*exc)
        return False


def xprof_trace(tag):
    """Device-profiler hook: one ``jax.profiler`` capture per
    (process, tag) into ``$F16_XPROF/<tag>`` — armed around the plan and
    serve dispatch sites so the first silicon session banks a real device
    profile without a second run. Unarmed (knob unset) or already
    captured → the no-op ``profiler_trace(None)``."""
    trace_dir = os.environ.get("F16_XPROF", "")
    if not trace_dir or tag in _xprof_done:
        return profiler_trace(None)
    _xprof_done.add(tag)
    return profiler_trace(os.path.join(trace_dir, tag))


_maybe_configure_from_env()

# Runs that never call shutdown() (the CLI verbs don't) still get the
# exit-time manifest facts and a flushed sink.
import atexit  # noqa: E402

atexit.register(shutdown)
