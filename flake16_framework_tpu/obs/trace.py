"""The ``trace`` CLI verb: convert one telemetry run (events.jsonl +
manifest.json) into Chrome-trace/Perfetto JSON.

    python -m flake16_framework_tpu trace [RUN_DIR] [--out FILE] \
        [--root DIR]

Spans become ``X`` (complete) duration events laid out on one lane per
emitting thread — span events carry ``tid`` since this PR; older logs
fall back to one lane per span-name family (``scores``, ``shap``, ...).
Counters and gauges become ``C`` counter tracks, and the point-like kinds
(fault, heartbeat, profile, stage, cost, journal, drain, restart) become
``i`` instants whose args carry the full event, so a 216-config sweep —
preemptions, journal replays and drains included — reads as a timeline
in chrome://tracing or https://ui.perfetto.dev instead of a JSONL
scroll.

``summarize_device_trace`` is the trace-summarization half of
tools/hw_trace.py (top device ops by total duration from a perfetto
``*.trace.json.gz``, mapped to HLO metadata where present), moved here so
both the scratch probes and future verbs share one parser; hw_trace.py
keeps a back-compat shim, the same pattern used when the telemetry drift
lint absorbed tools/check_telemetry_schema.py.
"""

import glob
import gzip
import json
import os
import sys
from collections import defaultdict

from flake16_framework_tpu.obs import report, schema

# Kinds rendered as point events; everything else schema-known is handled
# explicitly below.
_INSTANT_KINDS = ("fault", "heartbeat", "profile", "stage", "cost",
                  "journal", "drain", "restart", "metrics", "slo",
                  "flight", "perf")

_PID = 1  # single-process runs: one chrome "process" per run


def _micros(ts, t0):
    return max(0.0, (ts - t0) * 1e6)


def chrome_trace(manifest, events):
    """A Chrome-trace object ({"traceEvents": [...]}) for one run."""
    started = manifest.get("started_ts")
    ts_all = [e["ts"] for e in events
              if isinstance(e.get("ts"), (int, float))]
    t0 = started if isinstance(started, (int, float)) else (
        min(ts_all) if ts_all else 0.0)

    out = []
    argv = manifest.get("argv") or []
    pname = "flake16 " + " ".join(str(a) for a in argv[1:2]) if argv \
        else "flake16"
    out.append({"ph": "M", "pid": _PID, "name": "process_name",
                "args": {"name": pname.strip()}})

    tids = {}  # lane key (thread ident or span family) -> small tid

    def lane(ev):
        # Per-request lanes first: spans carrying a trace context render
        # on a ``request <id>`` lane beside the per-thread lanes, so one
        # Perfetto view shows a sampled request crossing the batcher.
        trace_id = ev.get("trace_id")
        if isinstance(trace_id, str) and trace_id:
            key = f"request {trace_id[:8]}"
        else:
            key = ev.get("tid")
            if key is None:  # pre-tid logs: lane per span-name family
                key = str(ev.get("name", "?")).split(".")[0]
        if key not in tids:
            tids[key] = len(tids) + 1
            label = f"thread {key}" if isinstance(key, int) else key
            out.append({"ph": "M", "pid": _PID, "tid": tids[key],
                        "name": "thread_name", "args": {"name": label}})
        return tids[key]

    for ev in events:
        kind = ev.get("kind")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        if kind == "span" and isinstance(ev.get("wall_s"), (int, float)):
            # the span event is stamped at exit; start = ts - wall
            wall = ev["wall_s"]
            args = {k: v for k, v in ev.items()
                    if k not in ("kind", "ts", "run", "name", "wall_s",
                                 "tid")}
            out.append({"ph": "X", "pid": _PID, "tid": lane(ev),
                        "ts": _micros(ts - wall, t0),
                        "dur": wall * 1e6, "cat": "span",
                        "name": ev.get("name", "?"), "args": args})
        elif kind == "counter" and isinstance(ev.get("total"),
                                              (int, float)):
            out.append({"ph": "C", "pid": _PID, "ts": _micros(ts, t0),
                        "name": ev.get("name", "?"),
                        "args": {"total": ev["total"]}})
        elif kind == "gauge" and isinstance(ev.get("value"), (int, float)):
            out.append({"ph": "C", "pid": _PID, "ts": _micros(ts, t0),
                        "name": ev.get("name", "?"),
                        "args": {"value": ev["value"]}})
        elif kind in _INSTANT_KINDS:
            args = {k: v for k, v in ev.items()
                    if k not in ("kind", "ts", "run")}
            name = kind if kind != "cost" else \
                f"cost {ev.get('span', '?')}"
            out.append({"ph": "i", "pid": _PID, "tid": 0, "s": "p",
                        "ts": _micros(ts, t0), "cat": kind, "name": name,
                        "args": args})

    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"run": manifest.get("run", "?"),
                          "schema": schema.TELEMETRY_SCHEMA}}


def write_trace(run_dir, out_path=None):
    """Render ``run_dir`` to Chrome-trace JSON at ``out_path`` (default
    ``<run_dir>/trace.json``); returns (path, trace object)."""
    manifest, events = report.load_run(run_dir)
    trace = chrome_trace(manifest, events)
    out_path = out_path or os.path.join(run_dir, "trace.json")
    from flake16_framework_tpu.utils.atomic import atomic_write

    with atomic_write(out_path, "w") as fd:
        json.dump(trace, fd)
    return out_path, trace


def trace_main(args, out=None):
    """CLI entry for the ``trace`` verb (``__main__.py``)."""
    out = out or sys.stdout
    root = None
    path = None
    out_path = None
    it = iter(args)
    for a in it:
        if a == "--out":
            out_path = next(it, None)
            if out_path is None:
                raise ValueError("--out needs a file argument")
        elif a == "--root":
            root = next(it, None)
            if root is None:
                raise ValueError("--root needs a directory argument")
        elif a.startswith("--"):
            raise ValueError(f"Unrecognized trace option {a!r}")
        elif path is None:
            path = a
        else:
            raise ValueError(f"Unrecognized trace argument {a!r}")
    run_dir = report.find_run_dir(path, root)
    out_path, trace = write_trace(run_dir, out_path)
    n = len(trace["traceEvents"])
    out.write(f"[{run_dir}]\nwrote {out_path} ({n} trace events) — load "
              "in chrome://tracing or https://ui.perfetto.dev\n")
    return out_path


# -- device-trace summarization (from tools/hw_trace.py) ----------------


def summarize_device_trace(trace_dir, top=25, out=None):
    """Sum device-track slice durations by op name from the newest
    perfetto trace under ``trace_dir``; prints the top ops and returns
    the aggregates (None when no trace exists)."""
    out = out or sys.stdout
    paths = sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True,
    ), key=os.path.getmtime)
    if not paths:
        out.write(f"no trace found under {trace_dir}\n")
        return None
    with gzip.open(paths[-1], "rt") as fd:
        data = json.load(fd)
    events = data.get("traceEvents", [])
    # device tracks: process names containing "TPU" / "Device"
    pid_name = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_name[e["pid"]] = e["args"].get("name", "")
    dur_by_name = defaultdict(float)
    count_by_name = defaultdict(int)
    total = 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        pname = pid_name.get(e.get("pid"), "")
        if not ("TPU" in pname or "Device" in pname or "/device" in pname):
            continue
        d = float(e.get("dur", 0.0))
        name = e.get("name", "?")
        dur_by_name[name] += d
        count_by_name[name] += 1
        total += d
    ranked = sorted(dur_by_name.items(), key=lambda kv: -kv[1])
    out.write(f"trace: {paths[-1]}\n")
    out.write(f"device total: {total / 1e6:.3f} s over "
              f"{sum(count_by_name.values())} slices\n")
    for name, d in ranked[:top]:
        out.write(f"{d / 1e6:9.3f} s  x{count_by_name[name]:<5d} "
                  f"{name[:100]}\n")
    return {
        "trace": paths[-1],
        "total_s": total / 1e6,
        "slices": sum(count_by_name.values()),
        "top": [{"name": n_, "dur_s": d / 1e6,
                 "count": count_by_name[n_]} for n_, d in ranked[:top]],
    }
