"""The ``trace`` CLI verb: convert one telemetry run (events.jsonl +
manifest.json) into Chrome-trace/Perfetto JSON.

    python -m flake16_framework_tpu trace [RUN_DIR] [--out FILE] \
        [--root DIR]
    python -m flake16_framework_tpu trace --fleet [ROOT] [--out FILE]

Spans become ``X`` (complete) duration events laid out on one lane per
emitting thread — span events carry ``tid`` since this PR; older logs
fall back to one lane per span-name family (``scores``, ``shap``, ...).
Counters and gauges become ``C`` counter tracks, and the point-like kinds
(fault, heartbeat, profile, stage, cost, journal, drain, restart) become
``i`` instants whose args carry the full event, so a 216-config sweep —
preemptions, journal replays and drains included — reads as a timeline
in chrome://tracing or https://ui.perfetto.dev instead of a JSONL
scroll.

``--fleet`` renders a WHOLE fleet's telemetry root (``serve --fleet``
with ``F16_TELEMETRY`` pointing at one directory: the router's run plus
every worker's) into a single merged Perfetto view: one process lane
per OS process — the router at pid 1, worker ``i`` (manifest
``fleet_worker``) at pid ``i + 2`` — against one shared epoch, with
Chrome flow arrows (``s``/``t``/``f``, id = trace_id) stitching each
sampled request's router-side ``fleet.request`` span to the worker-side
``serve.request`` spans it fanned out to. A hedged or failed-over
request therefore reads as ONE arrow chain crossing process lanes — the
cross-process trace-propagation witness (ISSUE 19 tentpole a).

``summarize_device_trace`` is the trace-summarization half of
tools/hw_trace.py (top device ops by total duration from a perfetto
``*.trace.json.gz``, mapped to HLO metadata where present), moved here so
both the scratch probes and future verbs share one parser; hw_trace.py
keeps a back-compat shim, the same pattern used when the telemetry drift
lint absorbed tools/check_telemetry_schema.py.
"""

import glob
import gzip
import json
import os
import sys
from collections import defaultdict

from flake16_framework_tpu.obs import core, report, schema

# Kinds rendered as point events; everything else schema-known is handled
# explicitly below.
_INSTANT_KINDS = ("fault", "heartbeat", "profile", "stage", "cost",
                  "journal", "drain", "restart", "metrics", "slo",
                  "flight", "perf")

_PID = 1  # single-process runs: one chrome "process" per run


def _micros(ts, t0):
    return max(0.0, (ts - t0) * 1e6)


# Request-scoped span names whose start points anchor cross-process flow
# arrows in the fleet-merged render: the router's per-request span plus
# the worker-side request span that adopts its trace context.
_FLOW_SPAN_NAMES = ("fleet.request", "serve.request")


def _run_t0(manifest, events):
    """One run's epoch: manifest started_ts, else the earliest event."""
    started = manifest.get("started_ts")
    if isinstance(started, (int, float)):
        return started
    ts_all = [e["ts"] for e in events
              if isinstance(e.get("ts"), (int, float))]
    return min(ts_all) if ts_all else 0.0


def _render_run(manifest, events, *, pid, t0, out, lanes=None,
                anchors=None, pname=None):
    """Append one run's Chrome-trace events to ``out`` as process
    ``pid`` against the (possibly shared) epoch ``t0``.

    ``lanes`` is the tid allocator — pass one dict across runs so two
    runs merged onto the same pid (a respawned worker re-using its
    index) cannot collide lanes. When ``anchors`` is a dict, every
    _FLOW_SPAN_NAMES span carrying a trace context records its start
    point into it (``trace_id -> [(start_us, pid, tid)]``) — the raw
    material for the fleet render's cross-process flow arrows."""
    if pname is None:
        argv = manifest.get("argv") or []
        pname = "flake16 " + " ".join(str(a) for a in argv[1:2]) \
            if argv else "flake16"
    out.append({"ph": "M", "pid": pid, "name": "process_name",
                "args": {"name": pname.strip()}})

    # lane key (thread ident or span family) -> small tid, per pid
    tids = lanes if lanes is not None else {}

    def lane(ev):
        # Per-request lanes first: spans carrying a trace context render
        # on a ``request <id>`` lane beside the per-thread lanes, so one
        # Perfetto view shows a sampled request crossing the batcher.
        trace_id = ev.get("trace_id")
        if isinstance(trace_id, str) and trace_id:
            key = f"request {trace_id[:8]}"
        else:
            key = ev.get("tid")
            if key is None:  # pre-tid logs: lane per span-name family
                key = str(ev.get("name", "?")).split(".")[0]
        key = (pid, key)
        if key not in tids:
            tids[key] = sum(1 for k in tids if k[0] == pid) + 1
            label = f"thread {key[1]}" \
                if isinstance(key[1], int) else key[1]
            out.append({"ph": "M", "pid": pid, "tid": tids[key],
                        "name": "thread_name", "args": {"name": label}})
        return tids[key]

    for ev in events:
        kind = ev.get("kind")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        if kind == "span" and isinstance(ev.get("wall_s"), (int, float)):
            # the span event is stamped at exit; start = ts - wall
            wall = ev["wall_s"]
            args = {k: v for k, v in ev.items()
                    if k not in ("kind", "ts", "run", "name", "wall_s",
                                 "tid")}
            tid = lane(ev)
            start_us = _micros(ts - wall, t0)
            out.append({"ph": "X", "pid": pid, "tid": tid,
                        "ts": start_us,
                        "dur": wall * 1e6, "cat": "span",
                        "name": ev.get("name", "?"), "args": args})
            if (anchors is not None
                    and ev.get("name") in _FLOW_SPAN_NAMES
                    and isinstance(ev.get("trace_id"), str)):
                anchors.setdefault(ev["trace_id"], []).append(
                    (start_us, pid, tid))
        elif kind == "counter" and isinstance(ev.get("total"),
                                              (int, float)):
            out.append({"ph": "C", "pid": pid, "ts": _micros(ts, t0),
                        "name": ev.get("name", "?"),
                        "args": {"total": ev["total"]}})
        elif kind == "gauge" and isinstance(ev.get("value"), (int, float)):
            out.append({"ph": "C", "pid": pid, "ts": _micros(ts, t0),
                        "name": ev.get("name", "?"),
                        "args": {"value": ev["value"]}})
        elif kind in _INSTANT_KINDS:
            args = {k: v for k, v in ev.items()
                    if k not in ("kind", "ts", "run")}
            name = kind if kind != "cost" else \
                f"cost {ev.get('span', '?')}"
            out.append({"ph": "i", "pid": pid, "tid": 0, "s": "p",
                        "ts": _micros(ts, t0), "cat": kind, "name": name,
                        "args": args})


def chrome_trace(manifest, events):
    """A Chrome-trace object ({"traceEvents": [...]}) for one run."""
    out = []
    _render_run(manifest, events, pid=_PID,
                t0=_run_t0(manifest, events), out=out)
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"run": manifest.get("run", "?"),
                          "schema": schema.TELEMETRY_SCHEMA}}


def fleet_chrome_trace(runs):
    """One MERGED Chrome-trace object for a fleet's telemetry runs
    (``runs`` = [(manifest, events), ...]: the router's run plus every
    worker's, all sharing one telemetry root).

    Layout: worker runs (manifest ``fleet_worker`` = i, stamped by
    serve/fleet.worker_main) land on pid ``i + 2``; the first non-worker
    run is the router at pid 1; any other non-worker run gets the next
    free pid. All runs render against ONE epoch (the earliest run's t0)
    so lanes line up. Every trace_id whose request spans appear in more
    than one process gets a Chrome flow chain (``s`` at the earliest
    span start, ``t`` steps, ``f``/``bp:e`` at the last) — in Perfetto
    that is an arrow from the router's ``fleet.request`` span to each
    worker ``serve.request`` span that carried the request (hedges and
    failover re-dispatches included, because they share the trace_id)."""
    t0s = [t for t in (_run_t0(m, e) for m, e in runs) if t > 0.0]
    t0 = min(t0s) if t0s else 0.0

    worker_pids = [m.get("fleet_worker") + 2 for m, _ in runs
                   if isinstance(m.get("fleet_worker"), int)]
    next_free = max([1] + worker_pids) + 1
    out = []
    lanes = {}
    anchors = {}
    names = {}  # pid -> process label (the drill asserts on these)
    router_seen = False
    run_ids = []
    for manifest, events in sorted(runs, key=lambda r: _run_t0(*r)):
        fw = manifest.get("fleet_worker")
        if isinstance(fw, int):
            pid, pname = fw + 2, f"worker {fw}"
        elif not router_seen:
            pid, pname, router_seen = 1, "flake16 router", True
        else:
            pid, pname, next_free = next_free, None, next_free + 1
        _render_run(manifest, events, pid=pid, t0=t0, out=out,
                    lanes=lanes, anchors=anchors, pname=pname)
        names.setdefault(pid, pname or "flake16")
        run_ids.append(manifest.get("run", "?"))

    n_flows = 0
    for trace_id, points in sorted(anchors.items()):
        if len({p[1] for p in points}) < 2:
            continue  # single-process request: nothing to stitch
        chain = sorted(points)
        n_flows += 1
        for i, (ts_us, pid, tid) in enumerate(chain):
            ph = "s" if i == 0 else \
                ("f" if i == len(chain) - 1 else "t")
            ev = {"ph": ph, "pid": pid, "tid": tid, "ts": ts_us,
                  "cat": "fleet", "name": "request", "id": trace_id}
            if ph == "f":
                ev["bp"] = "e"  # bind to the enclosing slice
            out.append(ev)

    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"fleet": True, "runs": run_ids,
                          "processes": {str(p): n
                                        for p, n in sorted(names.items())},
                          "stitched_traces": n_flows,
                          "schema": schema.TELEMETRY_SCHEMA}}


def write_trace(run_dir, out_path=None):
    """Render ``run_dir`` to Chrome-trace JSON at ``out_path`` (default
    ``<run_dir>/trace.json``); returns (path, trace object)."""
    manifest, events = report.load_run(run_dir)
    trace = chrome_trace(manifest, events)
    out_path = out_path or os.path.join(run_dir, "trace.json")
    from flake16_framework_tpu.utils.atomic import atomic_write

    with atomic_write(out_path, "w") as fd:
        json.dump(trace, fd)
    return out_path, trace


def fleet_runs(root):
    """[(run_dir, manifest, events), ...] for every telemetry run under
    ``root``, oldest started first — the fleet render's input (worker
    runs are the ones whose manifest carries ``fleet_worker``)."""
    run_dirs = [
        d for d in (os.path.join(root, n) for n in
                    (os.listdir(root) if os.path.isdir(root) else ()))
        if os.path.isfile(os.path.join(d, schema.EVENTS_FILE))]
    loaded = [(d,) + report.load_run(d) for d in sorted(run_dirs)]
    return sorted(loaded, key=lambda r: _run_t0(r[1], r[2]))


def write_fleet_trace(root, out_path=None):
    """Render every run under the telemetry root ``root`` into ONE
    merged fleet Chrome-trace at ``out_path`` (default
    ``<root>/fleet_trace.json``); returns (path, trace object)."""
    root = root or core.default_root()
    runs = fleet_runs(root)
    if not runs:
        raise SystemExit(
            f"no telemetry runs under {root!r} — run serve --fleet with "
            "F16_TELEMETRY pointing at a directory first (see PROFILE.md "
            "'Fleet observability')")
    trace = fleet_chrome_trace([(m, e) for _, m, e in runs])
    out_path = out_path or os.path.join(root, "fleet_trace.json")
    from flake16_framework_tpu.utils.atomic import atomic_write

    with atomic_write(out_path, "w") as fd:
        json.dump(trace, fd)
    return out_path, trace


def trace_main(args, out=None):
    """CLI entry for the ``trace`` verb (``__main__.py``)."""
    out = out or sys.stdout
    root = None
    path = None
    out_path = None
    fleet = False
    it = iter(args)
    for a in it:
        if a == "--out":
            out_path = next(it, None)
            if out_path is None:
                raise ValueError("--out needs a file argument")
        elif a == "--root":
            root = next(it, None)
            if root is None:
                raise ValueError("--root needs a directory argument")
        elif a == "--fleet":
            fleet = True
        elif a.startswith("--"):
            raise ValueError(f"Unrecognized trace option {a!r}")
        elif path is None:
            path = a
        else:
            raise ValueError(f"Unrecognized trace argument {a!r}")
    if fleet:
        out_path, trace = write_fleet_trace(path or root, out_path)
        other = trace["otherData"]
        out.write(f"[{path or root or core.default_root()}]\nwrote "
                  f"{out_path} ({len(trace['traceEvents'])} trace events, "
                  f"{len(other['runs'])} runs, "
                  f"{other['stitched_traces']} stitched requests) — load "
                  "in chrome://tracing or https://ui.perfetto.dev\n")
        return out_path
    run_dir = report.find_run_dir(path, root)
    out_path, trace = write_trace(run_dir, out_path)
    n = len(trace["traceEvents"])
    out.write(f"[{run_dir}]\nwrote {out_path} ({n} trace events) — load "
              "in chrome://tracing or https://ui.perfetto.dev\n")
    return out_path


# -- device-trace summarization (from tools/hw_trace.py) ----------------


def summarize_device_trace(trace_dir, top=25, out=None):
    """Sum device-track slice durations by op name from the newest
    perfetto trace under ``trace_dir``; prints the top ops and returns
    the aggregates (None when no trace exists)."""
    out = out or sys.stdout
    paths = sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True,
    ), key=os.path.getmtime)
    if not paths:
        out.write(f"no trace found under {trace_dir}\n")
        return None
    with gzip.open(paths[-1], "rt") as fd:
        data = json.load(fd)
    events = data.get("traceEvents", [])
    # device tracks: process names containing "TPU" / "Device"
    pid_name = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_name[e["pid"]] = e["args"].get("name", "")
    dur_by_name = defaultdict(float)
    count_by_name = defaultdict(int)
    total = 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        pname = pid_name.get(e.get("pid"), "")
        if not ("TPU" in pname or "Device" in pname or "/device" in pname):
            continue
        d = float(e.get("dur", 0.0))
        name = e.get("name", "?")
        dur_by_name[name] += d
        count_by_name[name] += 1
        total += d
    ranked = sorted(dur_by_name.items(), key=lambda kv: -kv[1])
    out.write(f"trace: {paths[-1]}\n")
    out.write(f"device total: {total / 1e6:.3f} s over "
              f"{sum(count_by_name.values())} slices\n")
    for name, d in ranked[:top]:
        out.write(f"{d / 1e6:9.3f} s  x{count_by_name[name]:<5d} "
                  f"{name[:100]}\n")
    return {
        "trace": paths[-1],
        "total_s": total / 1e6,
        "slices": sum(count_by_name.values()),
        "top": [{"name": n_, "dur_s": d / 1e6,
                 "count": count_by_name[n_]} for n_, d in ranked[:top]],
    }
