"""Live metrics plane: pull-based registry + Prometheus-text exporter
(ISSUE 15 tentpole, part 1).

Everything the JSONL sink records is post-hoc; this module is the LIVE
complement — the same signals (queue depth, latency quantiles, ladder
rung, cache traffic, memory peaks) readable while the process runs,
in the Prometheus text exposition format, from a stdlib-HTTP thread.
Pull-based on purpose: sources are zero-cost closures sampled only when
a scraper actually asks, so an unscraped (or unserved) registry costs
nothing on the hot path — the same contract as ``F16_TELEMETRY``.

Wiring: ``serve --metrics-port N`` stands a ``MetricsServer`` up beside
the scoring service (port 0 = ephemeral, the smoke tool's mode);
``register_process_sources`` contributes the process-wide metrics and
``ScoringService`` registers its own serve/SLO sources on start. The
exporter reads collaborator modules via ``sys.modules`` only — metrics
must never be the thing that initializes jax or the AOT store.

``METRIC_CENSUS`` is the lint contract (analysis/rules_obs.py O105):
every ``obs.gauge``/``obs.counter_add`` literal name emitted anywhere in
the package must be declared here, so a metric cannot silently exist in
the event stream while being invisible to the live exporter's census.
"""

import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from flake16_framework_tpu.obs import core

# Every gauge/counter NAME the package emits through obs.gauge /
# obs.counter_add (rules_obs.O105 enforces the census both ways with
# the same two-way discipline as the event-kind census O104).
METRIC_CENSUS = frozenset({
    # serve/batcher.py + serve/service.py
    "serve.requests", "serve.queue_depth", "serve.p50_ms", "serve.p99_ms",
    "serve.inflight", "serve.shed",
    # obs/core.py memory gauges
    "host_rss_peak_mb", "device_mem_peak_mb",
    # parallel/sweep.py grid totals
    "configs", "folds", "trees",
    # pipeline.py SHAP grid totals
    "shap_configs",
})

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _format_value(value):
    value = float(value)
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(value)


def _escape_label(value):
    return str(value).replace("\\", r"\\").replace('"', r"\"") \
        .replace("\n", r"\n")


class MetricsRegistry:
    """Named pull sources. ``register(name, fn)`` takes a zero-arg
    closure returning a number (one sample), a dict (fan-out to
    ``name{name="key"}`` labeled samples), or None (source currently
    absent — e.g. device memory on CPU — and skipped, never 0-faked)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sources = {}  # name -> (kind, help text, fn)

    def register(self, name, fn, kind="gauge", help=""):
        with self._lock:
            self._sources[name] = (kind, help, fn)

    def unregister(self, name):
        with self._lock:
            self._sources.pop(name, None)

    def names(self):
        with self._lock:
            return sorted(self._sources)

    def collect(self):
        """[(name, kind, help, [(labels or None, value), ...])] for every
        source that currently yields a value; a raising or None source is
        skipped (the exporter must survive any collaborator's state)."""
        with self._lock:
            items = sorted(self._sources.items())
        out = []
        for name, (kind, help_text, fn) in items:
            try:
                value = fn()
            except Exception:
                continue
            if value is None:
                continue
            if isinstance(value, dict):
                samples = [({"name": str(k)}, float(v))
                           for k, v in sorted(value.items())
                           if isinstance(v, (int, float))]
                if not samples:
                    continue
            else:
                try:
                    samples = [(None, float(value))]
                except (TypeError, ValueError):
                    continue
            out.append((name, kind, help_text, samples))
        return out

    def render(self):
        """The Prometheus text exposition body (format 0.0.4)."""
        lines = []
        for name, kind, help_text, samples in self.collect():
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for labels, value in samples:
                label_s = ""
                if labels:
                    label_s = "{" + ",".join(
                        f'{k}="{_escape_label(v)}"'
                        for k, v in sorted(labels.items())) + "}"
                lines.append(f"{name}{label_s} {_format_value(value)}")
        return "\n".join(lines) + "\n"


def register_process_sources(registry):
    """Contribute the process-wide sources every exporter shares:
    memory peaks, ladder rung, AOT dispatch census, persistent-cache
    traffic, journal fold lag, and the telemetry counter totals. All
    read collaborators via ``sys.modules``/getattr — never initialize."""
    from flake16_framework_tpu.resilience import ladder

    registry.register(
        "f16_uptime_seconds", lambda: _run_uptime_s(),
        help="Wall seconds since the telemetry run started (or None "
             "with telemetry off).")
    registry.register(
        "f16_host_rss_peak_mb", core.host_rss_peak_mb,
        help="Peak resident set size of this process, MiB.")
    registry.register(
        "f16_device_mem_peak_mb", core.device_memory_peak_mb,
        help="Peak device memory over local devices, MB (absent where "
             "the backend does not report it).")
    registry.register(
        "f16_ladder_halvings", lambda: ladder.state().halvings,
        help="Degradation-ladder chunk halvings taken (0 = top rung).")
    registry.register(
        "f16_ladder_cpu_fallback",
        lambda: int(ladder.state().cpu_fallback),
        help="1 while the ladder pins dispatches to host CPU.")
    registry.register(
        "f16_ladder_pallas_broken",
        lambda: int(ladder.state().pallas_broken)
        + len(ladder.state().pallas_broken_kernels),
        help="Pallas->xla rungs currently taken across kernels.")
    registry.register(
        "f16_aot_dispatches_total", lambda: _aot_stat("dispatches"),
        kind="counter",
        help="AOT executable dispatches since process start.")
    registry.register(
        "f16_aot_compiles_total", lambda: _aot_stat("compiles"),
        kind="counter",
        help="AOT executable compiles since process start.")
    registry.register(
        "f16_jax_cache_hits_total", lambda: _aot_cache_stat("hits"),
        kind="counter",
        help="Persistent XLA compilation-cache hits observed.")
    registry.register(
        "f16_jax_cache_misses_total", lambda: _aot_cache_stat("misses"),
        kind="counter",
        help="Persistent XLA compilation-cache misses observed.")
    registry.register(
        "f16_journal_fold_lag_seconds", _journal_fold_lag,
        help="Seconds since the last sweep-journal append in this "
             "process (absent before any append).")
    registry.register(
        "f16_events_total", _counter_totals, kind="counter",
        help="Telemetry counter totals by name (the obs.counter_add "
             "census, labeled).")
    return registry


def _run_uptime_s():
    state = core._state
    if state is None:
        return None
    import time

    return round(time.time() - state.t0, 3)


def _aot_stat(field):
    aot = sys.modules.get("flake16_framework_tpu.obs.aot")
    if aot is None:
        return None
    return int(aot.dispatch_stats().get(field, 0))


def _aot_cache_stat(field):
    aot = sys.modules.get("flake16_framework_tpu.obs.aot")
    if aot is None:
        return None
    return int(aot.cache_stats().get(field, 0))


def _journal_fold_lag():
    journal = sys.modules.get("flake16_framework_tpu.resilience.journal")
    if journal is None:
        return None
    return journal.fold_lag_s()


def _counter_totals():
    state = core._state
    if state is None:
        return None
    with core._lock:
        return dict(state.counters) or None


class MetricsServer:
    """The exporter: a ThreadingHTTPServer daemon thread serving
    ``GET /metrics`` off a registry. ``port=0`` binds an ephemeral port
    (the smoke tool reads ``self.port`` after construction); bound to
    loopback by default — exposing a fleet endpoint is the operator's
    explicit ``host=`` decision, not a default."""

    def __init__(self, registry, port=0, host="127.0.0.1"):
        self.registry = registry
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = server.registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass  # scrapes must not spam the serving process's stderr

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="f16-metrics",
            daemon=True)
        self._thread.start()
        core.event("metrics", action="serve", port=self.port,
                   n_metrics=len(self.registry.names()))
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        core.event("metrics", action="stop", port=self.port)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def validate_exposition(text):
    """Problems with a Prometheus text body (empty list = valid) — the
    grammar subset we emit: HELP/TYPE comments, bare and labeled samples
    with finite numeric values. Shared by tools/metrics_smoke.py and the
    tier-1 tests so the endpoint and the validator cannot drift."""
    import re

    problems = []
    typed = set()
    sample_re = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
        r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""
        r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\})?"
        r" -?[0-9.eE+-]+$")
    for i, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) < 4 or parts[3] not in (
                    "gauge", "counter", "histogram", "summary", "untyped"):
                problems.append(f"line {i}: malformed TYPE: {line!r}")
            else:
                typed.add(parts[2])
            continue
        if line.startswith("# HELP "):
            if len(line.split(None, 3)) < 4:
                problems.append(f"line {i}: malformed HELP: {line!r}")
            continue
        if line.startswith("#"):
            continue
        if not sample_re.match(line):
            problems.append(f"line {i}: malformed sample: {line!r}")
            continue
        name = re.split(r"[{ ]", line, maxsplit=1)[0]
        if name not in typed:
            problems.append(
                f"line {i}: sample {name!r} precedes its # TYPE line")
        try:
            float(line.rsplit(" ", 1)[1])
        except ValueError:
            problems.append(f"line {i}: non-numeric value: {line!r}")
    if not typed:
        problems.append("no metrics exposed")
    return problems
