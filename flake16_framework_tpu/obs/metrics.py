"""Live metrics plane: pull-based registry + Prometheus-text exporter
(ISSUE 15 tentpole, part 1).

Everything the JSONL sink records is post-hoc; this module is the LIVE
complement — the same signals (queue depth, latency quantiles, ladder
rung, cache traffic, memory peaks) readable while the process runs,
in the Prometheus text exposition format, from a stdlib-HTTP thread.
Pull-based on purpose: sources are zero-cost closures sampled only when
a scraper actually asks, so an unscraped (or unserved) registry costs
nothing on the hot path — the same contract as ``F16_TELEMETRY``.

Wiring: ``serve --metrics-port N`` stands a ``MetricsServer`` up beside
the scoring service (port 0 = ephemeral, the smoke tool's mode);
``register_process_sources`` contributes the process-wide metrics and
``ScoringService`` registers its own serve/SLO sources on start. The
exporter reads collaborator modules via ``sys.modules`` only — metrics
must never be the thing that initializes jax or the AOT store.

``METRIC_CENSUS`` is the lint contract (analysis/rules_obs.py O105):
every ``obs.gauge``/``obs.counter_add`` literal name emitted anywhere in
the package must be declared here, so a metric cannot silently exist in
the event stream while being invisible to the live exporter's census.
"""

import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from flake16_framework_tpu.obs import core

# Every gauge/counter NAME the package emits through obs.gauge /
# obs.counter_add (rules_obs.O105 enforces the census both ways with
# the same two-way discipline as the event-kind census O104).
METRIC_CENSUS = frozenset({
    # serve/batcher.py + serve/service.py
    "serve.requests", "serve.queue_depth", "serve.p50_ms", "serve.p99_ms",
    "serve.inflight", "serve.shed",
    # serve/router.py fleet aggregates (ISSUE 19 maintenance tick)
    "fleet.rps", "fleet.queue_depth", "fleet.inflight", "fleet.workers_up",
    # obs/core.py memory gauges
    "host_rss_peak_mb", "device_mem_peak_mb",
    # parallel/sweep.py grid totals
    "configs", "folds", "trees",
    # pipeline.py SHAP grid totals
    "shap_configs",
})

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _format_value(value):
    value = float(value)
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(value)


def _escape_label(value):
    return str(value).replace("\\", r"\\").replace('"', r"\"") \
        .replace("\n", r"\n")


class MetricsRegistry:
    """Named pull sources. ``register(name, fn)`` takes a zero-arg
    closure returning a number (one sample), a dict (fan-out to
    ``name{name="key"}`` labeled samples — ``label=`` picks the label
    key, e.g. ``worker`` for the federated fleet sources), or None
    (source currently absent — e.g. device memory on CPU — and skipped,
    never 0-faked)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sources = {}  # name -> (kind, help text, fn, label key)

    def register(self, name, fn, kind="gauge", help="", label="name"):
        with self._lock:
            self._sources[name] = (kind, help, fn, str(label))

    def unregister(self, name):
        with self._lock:
            self._sources.pop(name, None)

    def names(self):
        with self._lock:
            return sorted(self._sources)

    def collect(self):
        """[(name, kind, help, [(labels or None, value), ...])] for every
        source that currently yields a value; a raising or None source is
        skipped (the exporter must survive any collaborator's state)."""
        with self._lock:
            items = sorted(self._sources.items())
        out = []
        for name, (kind, help_text, fn, label_key) in items:
            try:
                value = fn()
            except Exception:
                continue
            if value is None:
                continue
            if isinstance(value, dict):
                samples = [({label_key: str(k)}, float(v))
                           for k, v in sorted(value.items())
                           if isinstance(v, (int, float))]
                if not samples:
                    continue
            else:
                try:
                    samples = [(None, float(value))]
                except (TypeError, ValueError):
                    continue
            out.append((name, kind, help_text, samples))
        return out

    def render(self):
        """The Prometheus text exposition body (format 0.0.4)."""
        lines = []
        for name, kind, help_text, samples in self.collect():
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for labels, value in samples:
                label_s = ""
                if labels:
                    label_s = "{" + ",".join(
                        f'{k}="{_escape_label(v)}"'
                        for k, v in sorted(labels.items())) + "}"
                lines.append(f"{name}{label_s} {_format_value(value)}")
        return "\n".join(lines) + "\n"


def register_process_sources(registry):
    """Contribute the process-wide sources every exporter shares:
    memory peaks, ladder rung, AOT dispatch census, persistent-cache
    traffic, journal fold lag, and the telemetry counter totals. All
    read collaborators via ``sys.modules``/getattr — never initialize."""
    from flake16_framework_tpu.resilience import ladder

    registry.register(
        "f16_uptime_seconds", lambda: _run_uptime_s(),
        help="Wall seconds since the telemetry run started (or None "
             "with telemetry off).")
    registry.register(
        "f16_host_rss_peak_mb", core.host_rss_peak_mb,
        help="Peak resident set size of this process, MiB.")
    registry.register(
        "f16_device_mem_peak_mb", core.device_memory_peak_mb,
        help="Peak device memory over local devices, MB (absent where "
             "the backend does not report it).")
    registry.register(
        "f16_ladder_halvings", lambda: ladder.state().halvings,
        help="Degradation-ladder chunk halvings taken (0 = top rung).")
    registry.register(
        "f16_ladder_cpu_fallback",
        lambda: int(ladder.state().cpu_fallback),
        help="1 while the ladder pins dispatches to host CPU.")
    registry.register(
        "f16_ladder_pallas_broken",
        lambda: int(ladder.state().pallas_broken)
        + len(ladder.state().pallas_broken_kernels),
        help="Pallas->xla rungs currently taken across kernels.")
    registry.register(
        "f16_aot_dispatches_total", lambda: _aot_stat("dispatches"),
        kind="counter",
        help="AOT executable dispatches since process start.")
    registry.register(
        "f16_aot_compiles_total", lambda: _aot_stat("compiles"),
        kind="counter",
        help="AOT executable compiles since process start.")
    registry.register(
        "f16_jax_cache_hits_total", lambda: _aot_cache_stat("hits"),
        kind="counter",
        help="Persistent XLA compilation-cache hits observed.")
    registry.register(
        "f16_jax_cache_misses_total", lambda: _aot_cache_stat("misses"),
        kind="counter",
        help="Persistent XLA compilation-cache misses observed.")
    registry.register(
        "f16_journal_fold_lag_seconds", _journal_fold_lag,
        help="Seconds since the last sweep-journal append in this "
             "process (absent before any append).")
    registry.register(
        "f16_events_total", _counter_totals, kind="counter",
        help="Telemetry counter totals by name (the obs.counter_add "
             "census, labeled).")
    return registry


def register_fleet_sources(registry, router, *, scrape_timeout_s=0.5):
    """Federated fleet sources (ISSUE 19 tentpole b): ONE endpoint for
    the whole fleet. Per-worker series are labeled ``worker="<i>"`` and
    come from the heartbeat snapshot each routing link already carries,
    backfilled by an on-demand ``stats`` scrape (a side connection, see
    router.scrape_worker_stats) for an up worker whose heartbeat has
    gone stale; fleet aggregates come from the router's own accounting
    (latency ring, rps window, SLO monitor, failover records). The
    per-worker view is sampled once per scrape pass — a 250 ms TTL
    cache shared by every source below — so one GET costs at most one
    heartbeat sweep plus one scrape round per stale worker."""
    import time as _time

    cache = {"t": -1e9, "view": {}}
    cache_lock = threading.Lock()

    def _view():
        with cache_lock:
            now = _time.monotonic()
            if now - cache["t"] >= 0.25:
                view = {}
                stale = []
                for link in router.links:
                    snap = link.snapshot()
                    hb = dict(snap["hb"])
                    if snap["up"] and (not hb or snap["hb_age_s"]
                                       > router.stall_s):
                        stale.append(link.index)
                    view[link.index] = {"up": snap["up"],
                                        "pending": snap["pending"],
                                        "hb": hb}
                if stale:
                    scraped = router.scrape_worker_stats(
                        indices=stale, timeout_s=scrape_timeout_s)
                    for idx, stats in scraped.items():
                        hb = view[idx]["hb"]
                        for field in ("queue_depth", "requests",
                                      "p50_ms", "p99_ms"):
                            if stats.get(field) is not None:
                                hb[field] = stats[field]
                        hb["quarantined"] = sorted(
                            stats.get("quarantined") or ())
                cache["view"] = view
                cache["t"] = now
            return cache["view"]

    def per_worker(field):
        def sample():
            out = {}
            for idx, w in _view().items():
                v = w["hb"].get(field)
                if isinstance(v, bool):
                    out[str(idx)] = int(v)
                elif isinstance(v, (int, float)):
                    out[str(idx)] = v
            return out or None
        return sample

    registry.register(
        "f16_fleet_worker_up",
        lambda: {str(i): int(w["up"]) for i, w in _view().items()},
        label="worker",
        help="1 while the router's link to this worker is up.")
    registry.register(
        "f16_fleet_worker_pending",
        lambda: {str(i): w["pending"] for i, w in _view().items()},
        label="worker",
        help="Requests pending on this worker's link, router side.")
    registry.register(
        "f16_fleet_worker_queue_depth", per_worker("queue_depth"),
        label="worker",
        help="Worker-reported request queue depth (heartbeat/scrape).")
    registry.register(
        "f16_fleet_worker_inflight", per_worker("inflight"),
        label="worker",
        help="Worker-reported microbatches inside a dispatch.")
    registry.register(
        "f16_fleet_worker_requests_total", per_worker("requests"),
        kind="counter", label="worker",
        help="Requests completed by this worker since its start.")
    registry.register(
        "f16_fleet_worker_p50_ms", per_worker("p50_ms"), label="worker",
        help="Worker-local p50 request latency, ms.")
    registry.register(
        "f16_fleet_worker_p99_ms", per_worker("p99_ms"), label="worker",
        help="Worker-local p99 request latency, ms.")
    registry.register(
        "f16_fleet_worker_burn_fast", per_worker("burn_fast"),
        label="worker",
        help="Worker-local SLO fast-window burn (absent without a "
             "worker SLO monitor).")
    registry.register(
        "f16_fleet_worker_shedding", per_worker("shedding"),
        label="worker",
        help="1 while this worker's own SLO monitor is shedding.")

    registry.register(
        "f16_fleet_workers_up",
        lambda: sum(1 for w in _view().values() if w["up"]),
        help="Worker links currently up.")
    registry.register(
        "f16_fleet_rps", router.fleet_rps,
        help="Fleet-wide completed requests per second (router's "
             "sliding window).")
    registry.register(
        "f16_fleet_queue_depth",
        lambda: sum(w["hb"].get("queue_depth", 0)
                    for w in _view().values()),
        help="Sum of worker-reported queue depths.")
    registry.register(
        "f16_fleet_inflight",
        lambda: sum(w["hb"].get("inflight", 0)
                    for w in _view().values()),
        help="Sum of worker-reported inflight microbatches.")
    registry.register(
        "f16_fleet_quarantined",
        lambda: len({q for w in _view().values()
                     for q in (w["hb"].get("quarantined") or ())}),
        help="Distinct models quarantined anywhere in the fleet.")
    registry.register(
        "f16_fleet_requests_total",
        lambda: router.latency.snapshot()["count"], kind="counter",
        help="Requests completed through the router.")
    registry.register(
        "f16_fleet_p50_ms",
        lambda: router.latency.snapshot()["p50_ms"],
        help="Router-observed p50 request latency, ms.")
    registry.register(
        "f16_fleet_p99_ms",
        lambda: router.latency.snapshot()["p99_ms"],
        help="Router-observed p99 request latency, ms.")
    registry.register(
        "f16_fleet_hedges_total", lambda: router.hedges, kind="counter",
        help="Hedge duplicates sent.")
    registry.register(
        "f16_fleet_hedge_coalesced_total",
        lambda: router.hedge_coalesced, kind="counter",
        help="Hedge-loser responses coalesced.")
    registry.register(
        "f16_fleet_redispatches_total",
        lambda: router.redispatches, kind="counter",
        help="Failover/retriable re-dispatches.")
    registry.register(
        "f16_fleet_failovers_total",
        lambda: len(router.failovers), kind="counter",
        help="Closed failover windows (link deaths recovered).")
    if router.slo is not None:
        registry.register(
            "f16_fleet_burn_fast", lambda: router.slo.burn_fast,
            help="Fleet SLO burn over the fast window (1.0 = on "
                 "budget).")
        registry.register(
            "f16_fleet_burn_slow", lambda: router.slo.burn_slow,
            help="Fleet SLO burn over the slow window.")
        registry.register(
            "f16_fleet_slo_breaches_total",
            lambda: router.slo.breaches, kind="counter",
            help="Fleet-level burn-rate breaches recorded.")
    return registry


def _run_uptime_s():
    state = core._state
    if state is None:
        return None
    import time

    return round(time.time() - state.t0, 3)


def _aot_stat(field):
    aot = sys.modules.get("flake16_framework_tpu.obs.aot")
    if aot is None:
        return None
    return int(aot.dispatch_stats().get(field, 0))


def _aot_cache_stat(field):
    aot = sys.modules.get("flake16_framework_tpu.obs.aot")
    if aot is None:
        return None
    return int(aot.cache_stats().get(field, 0))


def _journal_fold_lag():
    journal = sys.modules.get("flake16_framework_tpu.resilience.journal")
    if journal is None:
        return None
    return journal.fold_lag_s()


def _counter_totals():
    state = core._state
    if state is None:
        return None
    with core._lock:
        return dict(state.counters) or None


class MetricsServer:
    """The exporter: a ThreadingHTTPServer daemon thread serving
    ``GET /metrics`` off a registry. ``port=0`` binds an ephemeral port
    (the smoke tool reads ``self.port`` after construction); bound to
    loopback by default — exposing a fleet endpoint is the operator's
    explicit ``host=`` decision, not a default."""

    def __init__(self, registry, port=0, host="127.0.0.1"):
        self.registry = registry
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = server.registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass  # scrapes must not spam the serving process's stderr

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="f16-metrics",
            daemon=True)
        self._thread.start()
        core.event("metrics", action="serve", port=self.port,
                   n_metrics=len(self.registry.names()))
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        core.event("metrics", action="stop", port=self.port)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def validate_exposition(text):
    """Problems with a Prometheus text body (empty list = valid) — the
    grammar subset we emit: HELP/TYPE comments, bare and labeled samples
    with finite numeric values. Shared by tools/metrics_smoke.py and the
    tier-1 tests so the endpoint and the validator cannot drift."""
    import re

    problems = []
    typed = set()
    sample_re = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
        r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""
        r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\})?"
        r" -?[0-9.eE+-]+$")
    for i, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) < 4 or parts[3] not in (
                    "gauge", "counter", "histogram", "summary", "untyped"):
                problems.append(f"line {i}: malformed TYPE: {line!r}")
            else:
                typed.add(parts[2])
            continue
        if line.startswith("# HELP "):
            if len(line.split(None, 3)) < 4:
                problems.append(f"line {i}: malformed HELP: {line!r}")
            continue
        if line.startswith("#"):
            continue
        if not sample_re.match(line):
            problems.append(f"line {i}: malformed sample: {line!r}")
            continue
        name = re.split(r"[{ ]", line, maxsplit=1)[0]
        if name not in typed:
            problems.append(
                f"line {i}: sample {name!r} precedes its # TYPE line")
        try:
            float(line.rsplit(" ", 1)[1])
        except ValueError:
            problems.append(f"line {i}: non-numeric value: {line!r}")
    if not typed:
        problems.append("no metrics exposed")
    return problems
