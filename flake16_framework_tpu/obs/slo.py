"""SLO monitor: multi-window burn-rate evaluation that ACTUATES
(ISSUE 15 tentpole, part 3).

ROADMAP item 5 specifies load-shedding "driven by the existing telemetry
gauges" — this closes that loop. The serving stack declares objectives
(p99 latency, error rate) in its config; the monitor folds every
completed request into two sliding windows (fast + slow, the classic
multi-window burn-rate alarm: the fast window reacts, the slow window
keeps one latency spike from flapping the fleet) and on each evaluation
compares the measured burn — the rate at which the error/latency budget
is being spent, 1.0 = exactly on budget — against trip thresholds.

Transitions do two things, in order:

- **actuate**: entering breach starts SHEDDING (``serve``'s admission
  path rejects new submits with ``RequestRejected`` while
  ``monitor.shedding`` — bounded-admission rejection, the queue never
  grows into the latency it is supposed to cure) and, when configured,
  steps the pallas→xla degradation ladder
  (``ladder.mark_pallas_broken``) so the hot kernel sheds compile/replay
  risk too; recovery clears shedding and releases the rung — but only
  the rung the monitor itself took (a rung taken by a real Mosaic fault
  stays down).
- **witness**: every transition emits an ``slo`` event with both burns
  (the drill in tests/test_obs_plane.py asserts the whole loop from
  these events alone).
"""

import threading
import time

from flake16_framework_tpu.obs import core


class SLOConfig:
    """Declared objectives + evaluation windows for one serving process.

    ``latency_budget``/``error_budget`` are the tolerated fractions of
    requests over-objective / failed; burn = measured fraction divided
    by budget (1.0 = spending exactly on budget). A breach requires BOTH
    windows >= ``shed_burn``; recovery requires the fast window back
    under ``clear_burn``. ``min_events`` keeps an idle or cold window
    from evaluating on noise."""

    __slots__ = ("p99_ms", "latency_budget", "error_budget",
                 "fast_window_s", "slow_window_s", "shed_burn",
                 "clear_burn", "min_events", "degrade", "kernel")

    def __init__(self, p99_ms=50.0, latency_budget=0.05, error_budget=0.02,
                 fast_window_s=5.0, slow_window_s=30.0, shed_burn=2.0,
                 clear_burn=1.0, min_events=8, degrade=True,
                 kernel="shap"):
        self.p99_ms = float(p99_ms)
        self.latency_budget = float(latency_budget)
        self.error_budget = float(error_budget)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.shed_burn = float(shed_burn)
        self.clear_burn = float(clear_burn)
        self.min_events = int(min_events)
        self.degrade = bool(degrade)
        self.kernel = kernel

    def describe(self):
        return {name: getattr(self, name) for name in self.__slots__}


class SLOMonitor:
    """Feed with ``observe``; poll with ``evaluate`` (the batcher calls
    it once per dispatched batch — evaluation is O(window) over a few
    thousand samples, noise next to a dispatch). ``shedding`` is the
    admission path's single-read gate."""

    def __init__(self, config=None):
        self.config = config or SLOConfig()
        self._lock = threading.Lock()
        self._samples = []  # (ts, latency_ms or None, error) oldest-first
        self.shedding = False
        self.burn_fast = 0.0
        self.burn_slow = 0.0
        self.worst_burn_fast = 0.0
        self.worst_burn_slow = 0.0
        self.breaches = 0
        self.recoveries = 0
        self.shed_total = 0
        self.observed_total = 0
        # Cumulative (never-pruned) budget accounting: unlike the burn
        # windows these survive the sliding horizon, so two snapshots
        # bracket an interval's error-budget spend exactly — the fleet
        # rolling-restart annotation (ISSUE 19) is built on the deltas.
        self.total_errors = 0
        self.total_over_latency = 0
        self.time_in_degraded_s = 0.0
        self._degraded_since = None
        self._took_rung = False

    # -- feed ------------------------------------------------------------

    def observe(self, latency_ms=None, error=False, now=None):
        """One completed (or failed) request."""
        now = time.time() if now is None else now
        with self._lock:
            self._samples.append((now, latency_ms, bool(error)))
            self.observed_total += 1
            if error:
                self.total_errors += 1
            elif latency_ms is not None and latency_ms > self.config.p99_ms:
                self.total_over_latency += 1
            self._prune(now)

    def record_shed(self):
        """One admission rejected because of the shedding state — the
        accounting behind ``serve_shed_pct`` in the bench detail."""
        with self._lock:
            self.shed_total += 1
        core.counter_add("serve.shed")

    def _prune(self, now):
        horizon = now - self.config.slow_window_s
        drop = 0
        for ts, _, _ in self._samples:
            if ts >= horizon:
                break
            drop += 1
        if drop:
            del self._samples[:drop]

    # -- evaluate + actuate ----------------------------------------------

    def _window_burn(self, samples):
        cfg = self.config
        n = len(samples)
        if n < cfg.min_events:
            return 0.0
        over = sum(1 for _, lat, _ in samples
                   if lat is not None and lat > cfg.p99_ms)
        errors = sum(1 for _, _, err in samples if err)
        return max((over / n) / cfg.latency_budget,
                   (errors / n) / cfg.error_budget)

    def evaluate(self, now=None):
        """Recompute both burns and run the transition machine. Returns
        the current state dict (what the slo events carry)."""
        cfg = self.config
        now = time.time() if now is None else now
        with self._lock:
            self._prune(now)
            slow = list(self._samples)
            fast_horizon = now - cfg.fast_window_s
            fast = [s for s in slow if s[0] >= fast_horizon]
            self.burn_fast = self._window_burn(fast)
            self.burn_slow = self._window_burn(slow)
            self.worst_burn_fast = max(self.worst_burn_fast,
                                       self.burn_fast)
            self.worst_burn_slow = max(self.worst_burn_slow,
                                       self.burn_slow)
            lats = sorted(lat for _, lat, _ in fast if lat is not None)
            p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))] \
                if lats else 0.0
            err_rate = (sum(1 for _, _, e in fast if e) / len(fast)) \
                if fast else 0.0
            breach = (not self.shedding
                      and self.burn_fast >= cfg.shed_burn
                      and self.burn_slow >= cfg.shed_burn)
            recover = self.shedding and self.burn_fast < cfg.clear_burn
            if breach:
                self.shedding = True
                self.breaches += 1
                self._degraded_since = now
            elif recover:
                self.shedding = False
                self.recoveries += 1
                if self._degraded_since is not None:
                    self.time_in_degraded_s += now - self._degraded_since
                    self._degraded_since = None
            state = {"burn_fast": round(self.burn_fast, 3),
                     "burn_slow": round(self.burn_slow, 3),
                     "p99_ms": round(float(p99), 3),
                     "error_rate": round(err_rate, 4),
                     "shed_total": self.shed_total,
                     "shedding": self.shedding}
        # Actuation + witness OUTSIDE the lock: the ladder and the sink
        # take their own locks, and observe() must never wait on them.
        if breach:
            degraded = False
            if cfg.degrade:
                from flake16_framework_tpu.resilience import ladder

                degraded = ladder.mark_pallas_broken(kernel=cfg.kernel)
                if degraded:
                    # _took_rung is shared with concurrent evaluate()
                    # callers (dispatcher pool) — flip it under the
                    # monitor lock, taken AFTER the ladder's released.
                    with self._lock:
                        self._took_rung = True
            core.event("slo", state="breach", degraded=degraded, **state)
        elif recover:
            with self._lock:
                took_rung, self._took_rung = self._took_rung, False
            if took_rung:
                from flake16_framework_tpu.resilience import ladder

                ladder.clear_pallas_broken(kernel=cfg.kernel)
            core.event("slo", state="recovered", **state)
        return state

    # -- reporting -------------------------------------------------------

    def budget_snapshot(self):
        """Cumulative event/error/over-latency totals. Two snapshots
        bracket an interval; :func:`budget_spend` turns the deltas into
        that interval's burn — how rolling restarts are annotated with
        the error budget they spent (ISSUE 19)."""
        with self._lock:
            return {"events": self.observed_total,
                    "errors": self.total_errors,
                    "over_latency": self.total_over_latency}

    def summary(self, now=None):
        """The bench/report rollup (BENCH_r10 detail fields)."""
        now = time.time() if now is None else now
        with self._lock:
            degraded_s = self.time_in_degraded_s
            if self._degraded_since is not None:
                degraded_s += now - self._degraded_since
            total = self.observed_total + self.shed_total
            return {
                "worst_burn_fast": round(self.worst_burn_fast, 3),
                "worst_burn_slow": round(self.worst_burn_slow, 3),
                "breaches": self.breaches,
                "recoveries": self.recoveries,
                "shed_total": self.shed_total,
                "serve_shed_pct": round(100.0 * self.shed_total / total, 3)
                if total else 0.0,
                "time_in_degraded_s": round(degraded_s, 3),
                "shedding": self.shedding,
                "objective_p99_ms": self.config.p99_ms,
            }


def budget_spend(before, after, config):
    """The error-budget spend of the interval two
    :meth:`SLOMonitor.budget_snapshot` calls bracket: the event/error/
    over-latency deltas plus ``burn`` — the interval's measured burn
    rate under ``config``'s budgets (same max-of-fractions math as
    :meth:`SLOMonitor._window_burn`, but over an exact interval instead
    of a sliding window). Zero events = zero burn: an idle interval
    spends nothing."""
    events = int(after["events"]) - int(before["events"])
    errors = int(after["errors"]) - int(before["errors"])
    over = int(after["over_latency"]) - int(before["over_latency"])
    burn = 0.0
    if events > 0:
        burn = max((over / events) / config.latency_budget,
                   (errors / events) / config.error_budget)
    return {"events": events, "errors": errors, "over_latency": over,
            "burn": round(burn, 3)}
