"""Flight recorder: a crash-surviving ring buffer of the last N
telemetry events (ISSUE 15 tentpole, part 4).

The JSONL sink is append-only and unbounded — perfect evidence, terrible
black box: a SIGKILL'd serving worker leaves a sink whose useful tail is
buried in hours of events, and a worker running with telemetry pointed
at a slow filesystem may lose its final seconds entirely to page-cache
latency. The flight recorder is the complement: a FIXED-SIZE mmap'd
ring file holding only the most recent events, written with the
journal's CRC record discipline (resilience/journal.py), so the parent
supervisor can replay a valid tail out of the corpse no matter where
the kill landed.

Arming — same contract as ``F16_TELEMETRY``: unset/empty = off with
zero overhead; ``F16_FLIGHT=1`` = ring at ``<run_dir>/flight.bin``;
any other value = the ring file path (what the supervisor and the
chaos drill use — the parent must know the path to dump it). When
armed, ``obs.core._emit`` mirrors every event into the ring.

On-disk format (PROFILE.md "Observability plane"):

- 64-byte header: ``<8sIIQQ`` — magic ``F16FLT01``, version, capacity
  (ring bytes, excluding the header), ``head`` and ``tail`` (logical
  monotonic byte offsets; the ring region holds bytes
  ``[head % cap, tail % cap)`` wrap-around).
- records: ``<II`` (payload length, crc32) + UTF-8 JSON payload, the
  journal's framing with JSON instead of pickle (the replayer runs in
  a DIFFERENT process — the supervisor — and must never unpickle a
  corpse's bytes).

Torn-tail rule (journal-style, longest valid prefix): the writer makes
room by advancing ``head`` past whole old records, writes the record
bytes, THEN publishes ``tail`` — so a kill between any two instructions
leaves ``[head, tail)`` a valid record sequence and at worst an
unpublished (invisible) torn record past ``tail``. ``replay`` walks
records from ``head``, validating length sanity + CRC, and stops at the
first invalid record with ``torn=True`` instead of failing.
"""

import json
import mmap
import os
import struct
import sys
import threading
import time
import zlib

_MAGIC = b"F16FLT01"
_VERSION = 1
_HEADER = struct.Struct("<8sIIQQ")  # magic, version, capacity, head, tail
HEADER_SIZE = 64
_REC = struct.Struct("<II")         # payload length, crc32(payload)
DEFAULT_CAPACITY = 1 << 18          # 256 KiB of tail ~ thousands of events


class FlightRecorder:
    """The writer half: an mmap'd ring this process appends events to.

    Opening RESETS the ring (head = tail = 0): one process = one flight;
    the previous occupant's tail is the supervisor's to dump BEFORE it
    restarts the child. ``record`` is called under obs.core's emit path
    only (telemetry on + F16_FLIGHT armed), so the disabled path stays
    zero-overhead."""

    def __init__(self, path, capacity=DEFAULT_CAPACITY):
        self.path = path
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._head = 0
        self._tail = 0
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            os.ftruncate(fd, HEADER_SIZE + self.capacity)
            self._mm = mmap.mmap(fd, HEADER_SIZE + self.capacity)
        finally:
            os.close(fd)
        self._write_header()

    def _write_header(self):
        _HEADER.pack_into(self._mm, 0, _MAGIC, _VERSION, self.capacity,
                          self._head, self._tail)

    def _record_size_at(self, pos):
        """Whole-record size (framing + payload) at logical offset
        ``pos`` — the writer's room-making step; [head, tail) is valid
        by construction so the prefix is always readable."""
        prefix = self._read_ring(pos, _REC.size)
        length, _ = _REC.unpack(prefix)
        return _REC.size + length

    def _read_ring(self, pos, n):
        cap = self.capacity
        off = pos % cap
        first = min(n, cap - off)
        out = self._mm[HEADER_SIZE + off:HEADER_SIZE + off + first]
        if first < n:
            out += self._mm[HEADER_SIZE:HEADER_SIZE + (n - first)]
        return out

    def _write_ring(self, pos, data):
        cap = self.capacity
        off = pos % cap
        first = min(len(data), cap - off)
        self._mm[HEADER_SIZE + off:HEADER_SIZE + off + first] = data[:first]
        if first < len(data):
            self._mm[HEADER_SIZE:HEADER_SIZE + len(data) - first] = \
                data[first:]

    def record(self, obj):
        """Append one event dict; oldest records fall off the ring."""
        payload = json.dumps(obj, default=str).encode()
        rec = _REC.pack(len(payload), zlib.crc32(payload)) + payload
        if len(rec) > self.capacity:
            return  # pathological single record; never wedge the ring
        with self._lock:
            # Make room: advance head past whole old records, publish it
            # BEFORE overwriting their bytes (a kill mid-write must not
            # leave head pointing into clobbered bytes).
            while self._tail + len(rec) - self._head > self.capacity:
                self._head += self._record_size_at(self._head)
            self._write_header()
            self._write_ring(self._tail, rec)
            self._tail += len(rec)
            self._write_header()

    def close(self):
        # Under the ring lock: a record() racing close() must either
        # complete against the live mmap or see the closed one's
        # ValueError — never interleave with flush (f16race dogfood).
        with self._lock:
            try:
                self._mm.flush()
                self._mm.close()
            except (ValueError, OSError):
                pass


# -- replay (the parent / report side; plain reads, no mmap) ------------


def replay(path):
    """(records, meta) from a flight ring file — the longest valid
    record prefix of ``[head, tail)``. ``meta`` carries head/tail, the
    record count, and ``torn`` (True when an invalid record cut the walk
    short — expected after a kill mid-append, never an error)."""
    with open(path, "rb") as fd:
        blob = fd.read()
    if len(blob) < HEADER_SIZE:
        raise ValueError(f"flight file {path!r} too short for a header")
    magic, version, cap, head, tail = _HEADER.unpack_from(blob, 0)
    if magic != _MAGIC:
        raise ValueError(f"flight file {path!r} has bad magic {magic!r}")
    ring = blob[HEADER_SIZE:HEADER_SIZE + cap]

    def ring_read(pos, n):
        off = pos % cap
        first = min(n, cap - off)
        out = ring[off:off + first]
        if first < n:
            out += ring[:n - first]
        return out

    records = []
    torn = False
    pos = head
    while pos + _REC.size <= tail:
        length, crc = _REC.unpack(ring_read(pos, _REC.size))
        if length > cap - _REC.size or pos + _REC.size + length > tail:
            torn = True
            break
        payload = ring_read(pos + _REC.size, length)
        if zlib.crc32(payload) != crc:
            torn = True
            break
        try:
            records.append(json.loads(payload))
        except ValueError:
            torn = True
            break
        pos += _REC.size + length
    if pos != tail and not torn:
        torn = True  # trailing bytes too short for a record prefix
    return records, {"head": head, "tail": tail, "capacity": cap,
                     "n": len(records), "torn": torn,
                     "valid_end": pos}


def last_gauges(records):
    """{gauge name: last value} over a replayed record list — the
    killed process's final readings (queue depth, p99, memory)."""
    out = {}
    for ev in records:
        if ev.get("kind") == "gauge" and isinstance(
                ev.get("value"), (int, float)):
            out[ev.get("name", "?")] = ev["value"]
    return out


def flush_gauges_to_manifest(records, root=None, out=None):
    """Merge a replayed flight's gauge last-values into the dead run's
    manifest.json (the ISSUE-15 satellite: a SIGKILL'd serve process
    keeps its final queue-depth/p99 readings even though its own
    heartbeat/shutdown flush never ran). The run directory is found by
    the records' ``run`` token under ``root`` (default: the telemetry
    root). Returns the list of manifest paths updated."""
    from flake16_framework_tpu.obs import core, schema
    from flake16_framework_tpu.utils.atomic import atomic_write

    root = root or core.default_root()
    updated = []
    by_run = {}
    for ev in records:
        run = ev.get("run")
        if isinstance(run, str):
            by_run.setdefault(run, []).append(ev)
    for run, evs in by_run.items():
        gauges = last_gauges(evs)
        if not gauges:
            continue
        path = os.path.join(root, f"run-{run}", schema.MANIFEST_FILE)
        if not os.path.isfile(path):
            continue
        try:
            with open(path) as fd:
                manifest = json.load(fd)
        except (OSError, ValueError):
            continue
        manifest.setdefault("gauges", {}).update(gauges)
        manifest["flight_dump_ts"] = round(time.time(), 4)
        with atomic_write(path, "w") as fd:
            json.dump(manifest, fd, indent=1, default=str)
        updated.append(path)
        if out is not None:
            out.write(f"flight: flushed {len(gauges)} gauge last-value(s) "
                      f"into {path}\n")
    return updated


def dump(path, out=None, last=40, flush_manifest=True):
    """Replay ``path`` and pretty-print its tail — the supervisor's
    child-death hook and the ``report --flight`` body. Also flushes
    gauge last-values into the dead run's manifest (see above) and
    writes the full replay next to the ring as ``<path>.dump.json``.
    Returns the (records, meta) pair; never raises on a torn tail."""
    from flake16_framework_tpu.obs import core
    from flake16_framework_tpu.utils.atomic import atomic_write

    out = out or sys.stdout
    records, meta = replay(path)
    core.event("flight", action="dump", path=str(path), n=meta["n"],
               torn=meta["torn"])
    out.write(f"flight {path}: {meta['n']} record(s), "
              f"bytes [{meta['head']}, {meta['tail']})"
              + (" — TORN tail (valid prefix shown)\n" if meta["torn"]
                 else "\n"))
    gauges = last_gauges(records)
    if gauges:
        out.write("final gauges: " + "  ".join(
            f"{k}={v}" for k, v in sorted(gauges.items())) + "\n")
    for ev in records[-last:]:
        ts = ev.get("ts")
        stamp = time.strftime("%H:%M:%S", time.localtime(ts)) \
            if isinstance(ts, (int, float)) else "?"
        fields = {k: v for k, v in ev.items()
                  if k not in ("kind", "ts", "run")}
        out.write(f"  {stamp} {ev.get('kind', '?'):<10} "
                  + " ".join(f"{k}={v}" for k, v in fields.items())[:160]
                  + "\n")
    dump_path = str(path) + ".dump.json"
    with atomic_write(dump_path, "w") as fd:
        json.dump({"meta": meta, "gauges": gauges, "records": records},
                  fd, indent=1, default=str)
    out.write(f"wrote {dump_path}\n")
    if flush_manifest:
        flush_gauges_to_manifest(records, out=out)
    return records, meta


def env_path(environ=None, run_dir=None):
    """The armed flight-ring path from ``F16_FLIGHT`` (None = off).
    ``1`` means ``<run_dir>/flight.bin`` — only resolvable with an
    active run; an explicit value is the path itself (the form the
    supervisor can dump).

    Under a serving fleet (ISSUE 18) every worker inherits the SAME
    ``F16_FLIGHT`` value from the fleet manager — without
    uniquification W workers would mmap one ring file and clobber each
    other's headers. When ``F16_FLEET_WORKER`` is present the path
    gains a ``.w<index>`` suffix before the extension
    (``flight.bin`` → ``flight.w2.bin``); the fleet manager computes
    the identical path with the worker's env to dump the corpse ring,
    and ``replay_dir`` merges a directory of per-worker rings."""
    env = os.environ if environ is None else environ
    raw = env.get("F16_FLIGHT", "")
    if not raw:
        return None
    if raw == "1":
        if not run_dir:
            return None
        path = os.path.join(run_dir, "flight.bin")
    else:
        path = raw
    worker = env.get("F16_FLEET_WORKER", "")
    if worker != "":
        stem, ext = os.path.splitext(path)
        path = f"{stem}.w{worker}{ext or '.bin'}"
    return path


def ring_worker_index(name):
    """The fleet worker index a ring filename encodes (the ``.w<i>``
    suffix ``env_path`` appends under ``F16_FLEET_WORKER``), or None for
    a non-worker ring (the router/parent's own ``flight.bin``)."""
    stem, ext = os.path.splitext(os.path.basename(name))
    stem, dot, tag = stem.rpartition(".")
    if dot and tag.startswith("w") and tag[1:].isdigit():
        return int(tag[1:])
    return None


def replay_dir(dirpath):
    """(records, metas) merged by timestamp over every flight ring in a
    directory — the fleet form of ``replay`` (one ring per worker; the
    merged stream is the fleet's interleaved last seconds). Non-ring
    files are skipped; per-ring metas carry each ring's path + torn
    flag plus the source count. Every replayed event is annotated with
    the ring it came out of — ``fleet_worker`` = the ``.w<i>`` index
    for a worker ring (ISSUE 19 satellite: the merged stream stays
    attributable per process after the sort interleaves it)."""
    records = []
    metas = []
    for name in sorted(os.listdir(dirpath)):
        if not name.endswith(".bin"):
            continue
        path = os.path.join(dirpath, name)
        try:
            recs, meta = replay(path)
        except (OSError, ValueError):
            continue
        worker = ring_worker_index(name)
        if worker is not None:
            recs = [dict(ev, fleet_worker=worker) for ev in recs]
        meta = dict(meta, path=path, worker=worker)
        metas.append(meta)
        records.extend(recs)
    records.sort(key=lambda ev: ev.get("ts") or 0.0)
    return records, {"rings": metas, "n": len(records),
                     "torn": any(m["torn"] for m in metas)}


def dump_dir(dirpath, out=None, last=60, flush_manifest=True):
    """Replay + pretty-print a DIRECTORY of flight rings merged by
    timestamp (``report --flight <dir>`` under a fleet). Same contract
    as ``dump``: never raises on torn tails, writes the merged replay
    as ``<dir>/flight.merged.dump.json``."""
    from flake16_framework_tpu.obs import core
    from flake16_framework_tpu.utils.atomic import atomic_write

    out = out or sys.stdout
    records, meta = replay_dir(dirpath)
    core.event("flight", action="dump-dir", path=str(dirpath),
               rings=len(meta["rings"]), n=meta["n"], torn=meta["torn"])
    out.write(f"flight dir {dirpath}: {len(meta['rings'])} ring(s), "
              f"{meta['n']} record(s) merged by timestamp"
              + (" — TORN tail(s)\n" if meta["torn"] else "\n"))
    for ring in meta["rings"]:
        who = (f" (worker {ring['worker']})"
               if ring.get("worker") is not None else "")
        out.write(f"  ring {ring['path']}{who}: {ring['n']} record(s)"
                  + (" TORN" if ring["torn"] else "") + "\n")
    gauges = last_gauges(records)
    if gauges:
        out.write("final gauges: " + "  ".join(
            f"{k}={v}" for k, v in sorted(gauges.items())) + "\n")
    for ev in records[-last:]:
        ts = ev.get("ts")
        stamp = time.strftime("%H:%M:%S", time.localtime(ts)) \
            if isinstance(ts, (int, float)) else "?"
        fw = ev.get("fleet_worker")
        who = f"w{fw}" if isinstance(fw, int) else "--"
        fields = {k: v for k, v in ev.items()
                  if k not in ("kind", "ts", "run", "fleet_worker")}
        out.write(f"  {stamp} {who:<3} {ev.get('kind', '?'):<10} "
                  + " ".join(f"{k}={v}" for k, v in fields.items())[:160]
                  + "\n")
    dump_path = os.path.join(dirpath, "flight.merged.dump.json")
    with atomic_write(dump_path, "w") as fd:
        json.dump({"meta": meta, "gauges": gauges, "records": records},
                  fd, indent=1, default=str)
    out.write(f"wrote {dump_path}\n")
    if flush_manifest:
        flush_gauges_to_manifest(records, out=out)
    return records, meta
