"""lockwatch — the runtime lock-order witness (f16race's dynamic rung).

Opt-in via ``F16_LOCKWATCH`` (armed from ``obs/__init__`` before any
package lock is created): wraps the ``threading.Lock``/``RLock``
factories with a tracing proxy that records, per thread, the stack of
held locks and every *order edge* — lock B acquired while A is held.
Each lock is identified by its **creation site** (``path:lineno`` of
the first non-``threading`` frame at construction), which is exactly
the site analysis/concurrency.py records for the static C201 model —
so :func:`reconcile` can check the dynamic graph observed during a
serve/chaos drill is cycle-free AND a subgraph of the statically
allowed order (the I301-style static-vs-runtime contract; PROFILE.md
"Concurrency audit").

Because ``Condition``/``Event``/``Semaphore``/``queue.Queue`` build on
the patched factories *by runtime lookup*, their internal locks are
traced too: repo-created sync objects map to repo sites; locks minted
inside the stdlib map to stdlib sites and are treated as *foreign* —
they join the cycle check (a real inversion is a real deadlock
wherever the locks live) but not the subgraph check (the static model
cannot see them).

``F16_LOCKWATCH=1`` dumps ``lockwatch.json`` (schema
``flake16-lockwatch-v1``) into the CWD at exit; any other non-empty
value is the output path. The tracer's own state is guarded by a raw
``_thread`` lock so it can never appear in its own graph.
"""

import atexit
import json
import os
import sys
import threading
import _thread

from flake16_framework_tpu.obs import schema

ENV_VAR = "F16_LOCKWATCH"

_state_lock = _thread.allocate_lock()
_tls = threading.local()
_installed = False
_orig = {}
_dump_path = None
_locks = {}      # site -> {"kind": str, "created": int}
_edges = {}      # (site_a, site_b) -> count
_foreign_releases = 0

_THIS_FILE = os.path.abspath(__file__)


def _norm(path):
    apath = os.path.abspath(path)
    cwd = os.getcwd()
    if apath == cwd or apath.startswith(cwd + os.sep):
        apath = os.path.relpath(apath, cwd)
    return apath.replace(os.sep, "/")


def _creation_site():
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(("threading.py",)) \
                and os.path.abspath(fn) != _THIS_FILE:
            return f"{_norm(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>:0"


def _held_stack():
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _note_acquire(site):
    stack = _held_stack()
    with _state_lock:
        for held in dict.fromkeys(stack):  # dedup, keep order
            if held != site:               # reentrancy is not an edge
                key = (held, site)
                _edges[key] = _edges.get(key, 0) + 1
    stack.append(site)


def _note_release(site):
    global _foreign_releases
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == site:
            del stack[i]
            return
    with _state_lock:
        _foreign_releases += 1  # released by a non-acquiring thread


class _TracedLock:
    """Delegating proxy over a real lock. Only the entry points that
    change ownership are intercepted; everything else (``locked``,
    ``_is_owned``, ``_release_save``/``_acquire_restore`` — the RLock
    protocol Condition.wait borrows) reaches the inner lock through
    ``__getattr__``. A waiting thread is blocked, not running user
    code, so leaving the site on this thread's stack across ``wait()``
    keeps the held-set sound."""

    __slots__ = ("_f16_inner", "_f16_site")

    def __init__(self, inner, site):
        object.__setattr__(self, "_f16_inner", inner)
        object.__setattr__(self, "_f16_site", site)

    def acquire(self, *args, **kw):
        got = self._f16_inner.acquire(*args, **kw)
        if got:
            _note_acquire(self._f16_site)
        return got

    def release(self):
        self._f16_inner.release()
        _note_release(self._f16_site)

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<f16-lockwatch {self._f16_site} {self._f16_inner!r}>"

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_f16_inner"), name)


def _factory(orig, kind):
    def make(*args, **kw):
        inner = orig(*args, **kw)
        site = _creation_site()
        with _state_lock:
            rec = _locks.setdefault(site, {"kind": kind, "created": 0})
            rec["created"] += 1
        return _TracedLock(inner, site)
    make._f16_orig = orig
    return make


def install():
    """Patch the threading lock factories. Idempotent."""
    global _installed
    if _installed:
        return
    _orig["Lock"] = threading.Lock
    _orig["RLock"] = threading.RLock
    threading.Lock = _factory(threading.Lock, "lock")
    threading.RLock = _factory(threading.RLock, "rlock")
    _installed = True


def uninstall():
    """Restore the original factories (existing proxies keep working)."""
    global _installed
    if not _installed:
        return
    threading.Lock = _orig.pop("Lock")
    threading.RLock = _orig.pop("RLock")
    _installed = False


def reset():
    """Drop recorded locks/edges (between in-process experiments)."""
    global _foreign_releases
    with _state_lock:
        _locks.clear()
        _edges.clear()
        _foreign_releases = 0


def installed():
    return _installed


def snapshot():
    """The dynamic lock-order document (schema flake16-lockwatch-v1)."""
    with _state_lock:
        locks = {s: dict(rec) for s, rec in _locks.items()}
        edges = sorted([a, b, n] for (a, b), n in _edges.items())
        foreign = _foreign_releases
    return {
        "schema": schema.LOCKWATCH_SCHEMA,
        "pid": os.getpid(),
        "locks": locks,
        "edges": edges,
        "foreign_releases": foreign,
    }


def dump(path=None):
    """Write the snapshot atomically; returns the path."""
    from flake16_framework_tpu.utils.atomic import atomic_write

    path = path or _dump_path or "lockwatch.json"
    with atomic_write(path, "w", fsync=False, encoding="utf-8") as fd:
        json.dump(snapshot(), fd, indent=1, sort_keys=True)
    return path


def maybe_install_from_env():
    """Arm from ``F16_LOCKWATCH`` and register the exit dump. Called at
    obs package import, BEFORE obs/core creates its module locks."""
    global _dump_path
    val = os.environ.get(ENV_VAR, "")
    if val in ("", "0"):
        return False
    _dump_path = val if val not in ("1", "true", "yes") \
        else "lockwatch.json"
    install()
    atexit.register(_atexit_dump)
    return True


def _atexit_dump():
    try:
        dump()
    except Exception:
        pass  # a failed witness dump must never mask the real exit


# -- reconciliation against the static C201 model -------------------------


def _rel_site(site, root):
    if not root:
        return site
    path, _, lineno = site.rpartition(":")
    apath = os.path.abspath(root)
    if os.path.isabs(path) and (path == apath
                                or path.startswith(apath + os.sep)):
        path = os.path.relpath(path, apath).replace(os.sep, "/")
    return f"{path}:{lineno}"


def reconcile(dynamic, static_model, root=None):
    """Check a :func:`snapshot` document against the static lock model
    (analysis/concurrency.build_lock_model). Returns::

        {"ok": bool, "cycle": [site, ...] | None,
         "known_locks": [lock_id, ...],           # dynamically observed
         "violations": [{"edge": [idA, idB], "why": ...}, ...],
         "checked_edges": int, "foreign_edges": int}

    ``ok`` means the full dynamic graph (foreign locks included) is
    cycle-free AND every edge between statically known locks lies on a
    statically allowed order path: ``why="inverted"`` marks a dynamic
    edge whose *reverse* the static model orders (a latent deadlock
    against some other code path), ``why="unmodeled"`` an edge the
    static pass never derived (its call-graph blind spot — model it or
    fix the nesting). ``root`` relativizes absolute sites recorded by a
    child process run from a different CWD."""
    from flake16_framework_tpu.analysis import concurrency as conc

    dyn_edges = [(e[0], e[1]) for e in dynamic.get("edges", ())]
    cycle = conc.find_edge_cycle(dyn_edges)

    site_to_id = {}
    for lid, rec in static_model.get("locks", {}).items():
        site_to_id[_rel_site(rec["site"], root)] = lid
    closure = conc.transitive_closure(static_model.get("edges", ()))

    known, violations, checked, foreign = set(), [], 0, 0
    for (a, b) in dyn_edges:
        ia = site_to_id.get(_rel_site(a, root))
        ib = site_to_id.get(_rel_site(b, root))
        if ia is None or ib is None:
            foreign += 1
            continue
        checked += 1
        if ib in closure.get(ia, ()):
            continue
        why = "inverted" if ia in closure.get(ib, ()) else "unmodeled"
        violations.append({"edge": [ia, ib], "why": why})
    for site in dynamic.get("locks", ()):
        lid = site_to_id.get(_rel_site(site, root))
        if lid is not None:
            known.add(lid)

    return {
        "ok": cycle is None and not violations,
        "cycle": cycle,
        "known_locks": sorted(known),
        "violations": violations,
        "checked_edges": checked,
        "foreign_edges": foreign,
    }
