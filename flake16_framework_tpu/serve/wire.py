"""Fleet wire protocol: length-prefixed JSON frames over a stream
socket (ISSUE 18 tentpole).

One frame = a 4-byte big-endian payload length followed by a UTF-8 JSON
document. Numpy arrays ride inside the JSON as tagged base64 blobs
(``{"__nd__": [shape], "dtype": ..., "b64": ...}``) so a scoring batch
crosses the socket as raw little-endian bytes, not a float-per-token
decimal list. The framing is deliberately the flight ring's discipline
minus the CRC — TCP/AF_UNIX already guarantees integrity; what the
length prefix buys is record boundaries a reader can trust after any
interleaving of sender threads (every ``send_msg`` writes its frame
under the caller's send lock in one ``sendall``).

Message grammar (schema tag ``flake16-fleet-wire-v1``; PROFILE.md
"Fleet serving" is the authoritative catalog):

router -> worker requests (``id`` is the router-minted request id —
the coalescing key for hedged duplicates):

    {"id": N, "op": "score", "model": mid, "kind": k, "x": <array>,
     "trace_id": t, "parent_id": s}                # trace ctx, sampled only
    {"id": N, "op": "ping"}
    {"id": N, "op": "stats"}
    {"id": N, "op": "drain", "deadline_s": S}

``trace_id``/``parent_id`` are the cross-process trace context (ISSUE
19): the router-minted ``obs.mint_trace()`` trace id plus the router's
request span id. Both appear ONLY when the router sampled the request
(``F16_TRACE_SAMPLE`` coin) — an unsampled request's frame is
byte-identical to the pre-trace wire, so the propagation is zero-cost
when tracing is off. The worker adopts the inbound context via
``obs.adopt_trace`` so its ``serve.request`` spans nest under the
router's span on the SAME trace id; hedged duplicates carry the same
context, which is what lets one fleet-merged Perfetto render stitch a
request across every process it touched.

worker -> router responses (matched to the pending request by ``id``):

    {"id": N, "ok": true,  "out": <array>}        # score
    {"id": N, "ok": true,  ...}                   # ping/stats/drain
    {"id": N, "ok": false, "error": msg, "retriable": bool,
     "error_type": name}

worker -> router pushes (no ``id``; the router's reader consumes them
out of band):

    {"hb": {"ts": ..., "worker": i, "pid": p, "queue_depth": d,
            "inflight": n, "p50_ms": ..., "p99_ms": ..., "requests": c,
            "shedding": bool, "burn_fast": ..., "burn_slow": ...,
            "quarantined": [...]}}

``retriable`` carries the :class:`~flake16_framework_tpu.serve.queue.
ServeError` client contract across the process boundary: True means the
worker never dispatched on the request's behalf (draining rejection,
queue full), so the router may re-dispatch the SAME request id to
another worker — the zero-drop half of rolling restarts.
"""

import base64
import json
import socket
import struct

import numpy as np

WIRE_SCHEMA = "flake16-fleet-wire-v1"

# Field census for the three frame kinds above — the single source of
# truth the O107 lint rule holds emitters and parsers to. A frame key
# that is not in its kind's census is wire drift: either the docstring
# grammar above and this census grow together (a deliberate protocol
# rev) or the emitter is wrong. Trace-context fields are first-class
# members of the request census (ISSUE 19), not an extension.
TRACE_FIELDS = frozenset({"trace_id", "parent_id"})
REQUEST_FIELDS = frozenset(
    {"id", "op", "model", "kind", "x", "deadline_s"}) | TRACE_FIELDS
RESPONSE_FIELDS = frozenset(
    {"id", "ok", "out", "error", "retriable", "error_type",
     "worker", "pid", "stats", "acct"})
PUSH_FIELDS = frozenset({"hb"})
WIRE_FIELDS = {
    "request": REQUEST_FIELDS,
    "response": RESPONSE_FIELDS,
    "push": PUSH_FIELDS,
}

_LEN = struct.Struct(">I")
# A score frame is <= bucket_max x n_features float32 + envelope; 64 MiB
# is orders of magnitude above any legal batch — a larger length prefix
# means a corrupt/foreign stream, better torn down than buffered.
MAX_FRAME = 64 << 20


class WireError(ConnectionError):
    """A framing violation (oversize length, truncated frame mid-read).
    Both sides treat it like a dead peer: tear the connection down."""


def _encode_arrays(obj):
    """Deep-copy ``obj`` with numpy arrays replaced by tagged b64 blobs."""
    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        return {"__nd__": list(a.shape), "dtype": str(a.dtype),
                "b64": base64.b64encode(a.tobytes()).decode("ascii")}
    if isinstance(obj, dict):
        return {k: _encode_arrays(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_encode_arrays(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


def _decode_hook(d):
    if "__nd__" in d and "b64" in d:
        arr = np.frombuffer(base64.b64decode(d["b64"]),
                            dtype=np.dtype(d.get("dtype", "float32")))
        return arr.reshape([int(s) for s in d["__nd__"]]).copy()
    return d


def pack(obj):
    """One wire frame (length prefix + JSON payload) for a message."""
    payload = json.dumps(_encode_arrays(obj), default=str).encode()
    if len(payload) > MAX_FRAME:
        raise WireError(f"frame of {len(payload)} bytes exceeds "
                        f"MAX_FRAME ({MAX_FRAME})")
    return _LEN.pack(len(payload)) + payload


def unpack_payload(payload):
    return json.loads(payload.decode(), object_hook=_decode_hook)


def send_msg(sock, obj):
    """Write one frame. The CALLER serializes concurrent senders (the
    router's per-link send lock, the worker's per-connection send lock)
    — one sendall per frame keeps records atomic under that lock."""
    sock.sendall(pack(obj))


def _recv_exact(sock, n):
    """Read exactly ``n`` bytes, or None on a clean EOF at a record
    boundary. EOF mid-record raises WireError (a torn frame)."""
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except (OSError, ValueError):
            chunk = b""
        if not chunk:
            if got == 0:
                return None
            raise WireError(f"peer closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_msg(sock):
    """Read one frame; None on clean EOF. Raises WireError on a torn or
    oversize frame (treat as a dead peer)."""
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (length,) = _LEN.unpack(head)
    if length > MAX_FRAME:
        raise WireError(f"frame length {length} exceeds MAX_FRAME")
    payload = _recv_exact(sock, length)
    if payload is None:
        raise WireError("peer closed between length prefix and payload")
    return unpack_payload(payload)


def connect_unix(path, timeout=None):
    """One connected AF_UNIX stream socket (the router's side)."""
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    if timeout is not None:
        sock.settimeout(timeout)
    sock.connect(path)
    sock.settimeout(None)
    return sock


def listen_unix(path, backlog=8):
    """One listening AF_UNIX socket (the worker's side); a stale socket
    file from a previous occupant is unlinked first."""
    try:
        import os

        os.unlink(path)
    except OSError:
        pass
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.bind(path)
    sock.listen(backlog)
    return sock
