"""serve — the always-on scoring service (ISSUE 6; ROADMAP item 4).

The batch verbs answer "re-run the study"; production traffic is "CI
finished, score these test runs now". This subsystem turns the trained
artifacts of a sweep into a latency-serving layer:

- ``registry``  — model registry keyed by trained-config artifact
  (config code + tree-structure/leaf-shape signature), with the sweep's
  scores ledger as the artifact source and pickle persistence;
- ``store``     — AOT executable store: predict and SHAP executables
  pre-compiled per registered batch shape through the shared
  ``obs.aot.AotExecutableCache`` (no telemetry gate — a service must hit
  its compiled programs whether or not F16_TELEMETRY is set);
- ``queue``     — the async request queue (submit -> future);
- ``batcher``   — shape-bucketed microbatcher: pads coalesced requests
  to a registered bucket, dispatches through the resilience guard with
  bounded in-flight batches, pallas->xla ladder + quarantine as the
  failover path;
- ``service``   — ``ScoringService``: the in-process client API plus
  p50/p99 latency and queue-depth emission through the existing
  telemetry spans/gauges (``report``/``trace`` work unchanged), and
  ``drain()`` — the graceful SIGTERM path (ISSUE 11b): admission
  close, in-flight completion, retriable rejection of unstarted
  requests, durable-state flush with a deadline that escalates to
  checkpoint-and-abort;
- ``cli``       — the ``serve`` CLI verb (``--hold`` = drain drill).

``hot_path`` marks request-path functions OUTSIDE serve/batcher.py and
serve/queue.py (which are hot-path scope by location) for f16lint's J601
rule: blocking device->host transfers (``block_until_ready``,
``np.asarray`` on device values, ``device_get``) stall the microbatch
pipeline and belong at batch boundaries, not per request.
"""


def hot_path(fn):
    """Mark ``fn`` as serve hot-path code for f16lint's J601 rule (no
    runtime behavior — a static-analysis anchor, like typing markers)."""
    fn.__f16_hot_path__ = True
    return fn


from flake16_framework_tpu.serve.queue import (  # noqa: E402,F401
    RequestQueue, RequestRejected, RetriableRejection, ScoreRequest,
    ServeError,
)
from flake16_framework_tpu.serve.registry import (  # noqa: E402,F401
    ModelRegistry, RegisteredModel, artifact_signature, configs_from_ledger,
    model_id_for,
)
from flake16_framework_tpu.serve.store import ExecutableStore  # noqa: E402,F401
from flake16_framework_tpu.serve.batcher import Microbatcher  # noqa: E402,F401
from flake16_framework_tpu.serve.service import (  # noqa: E402,F401
    LatencyStats, ScoringService,
)
