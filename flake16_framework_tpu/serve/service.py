"""ScoringService: the in-process client API over registry + store +
queue + microbatcher, with latency SLO telemetry.

``start()`` pre-compiles every registered model's predict and SHAP
executables at every bucket shape (under the ``serve.warm`` span — the
compile bill is paid at service start, never during a request) and
starts the batcher threads. ``submit`` returns the request future;
``score`` is the synchronous wrapper. p50/p99 latency and queue depth
flow through the existing telemetry gauges, so ``report`` and ``trace``
work unchanged on a serving run.
"""

import threading

import numpy as np

from flake16_framework_tpu import obs
from flake16_framework_tpu.serve.batcher import Microbatcher
from flake16_framework_tpu.serve.queue import (
    RequestQueue, RequestRejected, ScoreRequest,
)
from flake16_framework_tpu.serve.store import ExecutableStore, KINDS


class LatencyStats:
    """Thread-safe bounded ring of request latencies (ms) with p50/p99
    snapshots — the service's SLO instrument."""

    def __init__(self, window=2048):
        self._window = int(window)
        self._lock = threading.Lock()
        self._ring = []
        self._idx = 0
        self._count = 0

    def record(self, ms):
        with self._lock:
            if len(self._ring) < self._window:
                self._ring.append(float(ms))
            else:
                self._ring[self._idx] = float(ms)
                self._idx = (self._idx + 1) % self._window
            self._count += 1

    def snapshot(self):
        with self._lock:
            vals = sorted(self._ring)
            count = self._count
        if not vals:
            return {"count": 0, "p50_ms": None, "p99_ms": None}

        def pct(p):
            return vals[min(len(vals) - 1, round(p * (len(vals) - 1)))]

        return {"count": count, "p50_ms": round(pct(0.50), 3),
                "p99_ms": round(pct(0.99), 3)}


class ScoringService:
    """The always-on scoring service (in-process form).

    ``with ScoringService(registry) as svc: svc.score(mid, x)`` — or
    ``start()``/``stop()`` explicitly. Admission raises
    :class:`RequestRejected` (unknown/quarantined model, bad kind,
    oversize batch, full queue); a dispatch the resilience guard
    abandoned re-raises from ``result()`` as DispatchAbandoned.
    """

    def __init__(self, registry, *, buckets=(8, 32, 128), max_inflight=2,
                 queue_max=256, guard=None, donate=None):
        self.registry = registry
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.store = ExecutableStore(registry, donate=donate)
        self.requests = RequestQueue(maxsize=queue_max)
        self.latency = LatencyStats()
        self.batcher = Microbatcher(
            self.store, self.requests, buckets=self.buckets,
            max_inflight=max_inflight, guard=guard, stats=self.latency)
        self._started = False

    # -- lifecycle -------------------------------------------------------

    def start(self):
        """Warm every (model, kind, bucket) executable, then start the
        batcher threads. Compile errors on the xla arms propagate — an
        unservable registry must fail here, not at the first request."""
        with obs.span("serve.warm", key=f"models={len(self.registry)}"):
            for model in self.registry.models():
                self.store.warm(model, self.buckets)
        obs.manifest_update(
            verb="serve", serve_models=len(self.registry),
            serve_buckets=list(self.buckets))
        self.batcher.start()
        self._started = True
        return self

    def stop(self):
        self.requests.close()
        self.batcher.stop()
        self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- client API ------------------------------------------------------

    def _admit(self, model_id, x, kind):
        if kind not in KINDS:
            raise RequestRejected(f"unknown kind: {kind!r} (want {KINDS})")
        model = self.registry.get(model_id)
        if model is None:
            raise RequestRejected(f"model not registered: {model_id}")
        if model_id in self.batcher.quarantined:
            raise RequestRejected(
                f"model quarantined: {model_id} "
                f"[{self.batcher.quarantined[model_id]['fault_class']}]")
        x = np.asarray(x, dtype=np.float32)
        if x.ndim == 1:
            x = x[None, :]
        n_cols = len(model.cols)
        if x.ndim != 2:
            raise RequestRejected(f"want [n, features], got {x.shape}")
        if x.shape[1] != n_cols:
            if x.shape[1] > max(model.cols):
                x = x[:, list(model.cols)]  # full feature rows: select
            else:
                raise RequestRejected(
                    f"feature width {x.shape[1]} matches neither the "
                    f"config's {n_cols} columns nor the full set")
        if not 1 <= x.shape[0] <= self.buckets[-1]:
            raise RequestRejected(
                f"batch rows {x.shape[0]} outside [1, {self.buckets[-1]}]"
                " (split client-side)")
        return model, x

    def submit(self, model_id, x, kind="predict"):
        """Admit one request; returns the :class:`ScoreRequest` future."""
        _, x = self._admit(model_id, x, kind)
        return self.requests.submit(ScoreRequest(model_id, x, kind=kind))

    def score(self, model_id, x, kind="predict", timeout=None):
        """Synchronous submit+result."""
        return self.submit(model_id, x, kind=kind).result(timeout)

    def stats(self):
        snap = self.latency.snapshot()
        return {
            "models": self.registry.ids(),
            "requests": snap["count"],
            "p50_ms": snap["p50_ms"],
            "p99_ms": snap["p99_ms"],
            "queue_depth": self.requests.depth(),
            "quarantined": dict(self.batcher.quarantined),
        }
