"""ScoringService: the in-process client API over registry + store +
queue + microbatcher, with latency SLO telemetry.

``start()`` pre-compiles every registered model's predict and SHAP
executables at every bucket shape (under the ``serve.warm`` span — the
compile bill is paid at service start, never during a request) and
starts the batcher threads. ``submit`` returns the request future;
``score`` is the synchronous wrapper. p50/p99 latency and queue depth
flow through the existing telemetry gauges, so ``report`` and ``trace``
work unchanged on a serving run.

``drain()`` is the preemption path (ISSUE 11b): admission closes,
in-flight microbatches complete, queued-but-unstarted requests fail
with :class:`~flake16_framework_tpu.serve.queue.RetriableRejection`
(resubmit is safe — nothing was dispatched), and every durable serve
artifact flushes (registry index, AOT warm manifest, obs manifest).
Past the deadline the drain escalates to checkpoint-and-abort: the
flush still runs, handed-off batches fail with a plain ServeError.
Zero requests are ever silently dropped — each submitted future either
completes or raises.
"""

import os
import threading
import time

import numpy as np

from flake16_framework_tpu import obs
from flake16_framework_tpu.serve.batcher import Microbatcher
from flake16_framework_tpu.serve.queue import (
    RequestQueue, RequestRejected, RetriableRejection, ScoreRequest,
    ServeError,
)
from flake16_framework_tpu.serve.store import (
    ExecutableStore, KINDS, MANIFEST_FILE,
)


# The bucket ladder every serve entry point warms when nothing better is
# known — the fall-through side of the perfdb consult below.
DEFAULT_BUCKETS = (8, 32, 128)


def resolve_buckets(buckets=None):
    """The warm-bucket ladder for a service: an explicit ``buckets``
    wins untouched; ``None`` consults the performance observatory
    (obs/perfdb.serve_buckets, ISSUE 16d) for a recorded best-known
    ladder and falls through to DEFAULT_BUCKETS bit-identically when the
    database, the row, or a valid ``serve_buckets`` knob is absent."""
    if buckets is not None:
        return tuple(sorted(int(b) for b in buckets))
    from flake16_framework_tpu.obs import perfdb

    recorded = perfdb.serve_buckets()
    if recorded:
        return tuple(sorted(int(b) for b in recorded))
    return DEFAULT_BUCKETS


class LatencyStats:
    """Thread-safe bounded ring of request latencies (ms) with p50/p99
    snapshots — the service's SLO instrument."""

    def __init__(self, window=2048):
        self._window = int(window)
        self._lock = threading.Lock()
        self._ring = []
        self._idx = 0
        self._count = 0

    def record(self, ms):
        with self._lock:
            if len(self._ring) < self._window:
                self._ring.append(float(ms))
            else:
                self._ring[self._idx] = float(ms)
                self._idx = (self._idx + 1) % self._window
            self._count += 1

    def snapshot(self):
        with self._lock:
            vals = sorted(self._ring)
            count = self._count
        if not vals:
            return {"count": 0, "p50_ms": None, "p99_ms": None}

        def pct(p):
            return vals[min(len(vals) - 1, round(p * (len(vals) - 1)))]

        return {"count": count, "p50_ms": round(pct(0.50), 3),
                "p99_ms": round(pct(0.99), 3)}


class ScoringService:
    """The always-on scoring service (in-process form).

    ``with ScoringService(registry) as svc: svc.score(mid, x)`` — or
    ``start()``/``stop()`` explicitly. Admission raises
    :class:`RequestRejected` (unknown/quarantined model, bad kind,
    oversize batch, full queue); a dispatch the resilience guard
    abandoned re-raises from ``result()`` as DispatchAbandoned.
    """

    def __init__(self, registry, *, buckets=None, max_inflight=2,
                 queue_max=256, guard=None, donate=None, slo=None,
                 metrics_port=None):
        self.registry = registry
        self.buckets = resolve_buckets(buckets)
        self.store = ExecutableStore(registry, donate=donate)
        self.requests = RequestQueue(maxsize=queue_max)
        self.latency = LatencyStats()
        # ``slo`` is the declared-objectives config (obs.slo.SLOConfig,
        # True = defaults, None = no SLO loop — zero new hot-path work).
        self.slo = None
        if slo is not None and slo is not False:
            from flake16_framework_tpu.obs.slo import SLOConfig, SLOMonitor

            self.slo = SLOMonitor(
                SLOConfig() if slo is True else slo)
        self.batcher = Microbatcher(
            self.store, self.requests, buckets=self.buckets,
            max_inflight=max_inflight, guard=guard, stats=self.latency,
            monitor=self.slo)
        # ``metrics_port`` stands the Prometheus exporter up beside the
        # service (0 = ephemeral; None = off, same contract as the SLO).
        self.metrics_port = metrics_port
        self.metrics = None
        self._started = False

    # -- lifecycle -------------------------------------------------------

    def start(self):
        """Warm every (model, kind, bucket) executable, then start the
        batcher threads. Compile errors on the xla arms propagate — an
        unservable registry must fail here, not at the first request."""
        with obs.span("serve.warm", key=f"models={len(self.registry)}"):
            for model in self.registry.models():
                self.store.warm(model, self.buckets)
        obs.manifest_update(
            verb="serve", serve_models=len(self.registry),
            serve_buckets=list(self.buckets))
        if self.metrics_port is not None:
            self.metrics = self._make_metrics_server(self.metrics_port)
            self.metrics.start()
        self.batcher.start()
        self._started = True
        return self

    def stop(self):
        self.requests.close()
        self.batcher.stop()
        if self.metrics is not None:
            self.metrics.stop()
            self.metrics = None
        self._started = False

    def _make_metrics_server(self, port):
        """Registry with the process-wide sources plus this service's
        live serve/SLO sources, behind a loopback HTTP thread."""
        from flake16_framework_tpu.obs.metrics import (
            MetricsRegistry, MetricsServer, register_process_sources,
        )

        reg = MetricsRegistry()
        register_process_sources(reg)
        reg.register("f16_serve_queue_depth", self.requests.depth,
                     help="Requests queued awaiting coalescing.")
        reg.register("f16_serve_inflight",
                     lambda: self.batcher.inflight,
                     help="Microbatches currently inside a dispatch.")
        reg.register("f16_serve_quarantined",
                     lambda: len(self.batcher.quarantined),
                     help="Models quarantined after abandoned dispatches.")
        reg.register("f16_serve_requests_total",
                     lambda: self.latency.snapshot()["count"],
                     kind="counter",
                     help="Requests completed since service start.")
        reg.register("f16_serve_p50_ms",
                     lambda: self.latency.snapshot()["p50_ms"],
                     help="p50 request latency over the rolling window, "
                          "ms.")
        reg.register("f16_serve_p99_ms",
                     lambda: self.latency.snapshot()["p99_ms"],
                     help="p99 request latency over the rolling window, "
                          "ms.")
        if self.slo is not None:
            reg.register("f16_slo_burn_fast",
                         lambda: self.slo.burn_fast,
                         help="SLO burn rate over the fast window "
                              "(1.0 = on budget).")
            reg.register("f16_slo_burn_slow",
                         lambda: self.slo.burn_slow,
                         help="SLO burn rate over the slow window.")
            reg.register("f16_slo_shedding",
                         lambda: int(self.slo.shedding),
                         help="1 while admission is shedding load.")
            reg.register("f16_serve_shed_total",
                         lambda: self.slo.shed_total, kind="counter",
                         help="Admissions rejected by SLO shedding.")
            reg.register("f16_slo_time_in_degraded_seconds",
                         lambda: self.slo.summary()["time_in_degraded_s"],
                         help="Cumulative wall seconds spent shedding.")
        return MetricsServer(reg, port=port)

    def slo_summary(self):
        """The SLO rollup for bench/report (None without an SLO loop)."""
        return self.slo.summary() if self.slo is not None else None

    def drain(self, deadline_s=10.0):
        """Graceful drain (see module docstring): close admission, fail
        queued requests with RetriableRejection, let in-flight batches
        complete within ``deadline_s``, then flush durable state. Past
        the deadline, escalate to checkpoint-and-abort (handed-off
        batches fail; the flush still runs). Returns the accounting
        dict the drain drill asserts on: phase (complete|abort) plus
        completed / rejected / aborted request counts."""
        t0 = time.perf_counter()
        done_before = self.latency.snapshot()["count"]
        obs.event("drain", phase="begin", deadline_s=float(deadline_s))
        self.requests.close()
        queued = self.requests.drain_pending()
        rejection = RetriableRejection(
            "service draining; resubmit to the replacement service")
        for r in queued:
            r._fail(rejection)
        clean = self.batcher.stop(timeout=deadline_s)
        aborted = 0
        if not clean:
            aborted = self.batcher.abort_pending(ServeError(
                f"drain deadline ({deadline_s}s) exceeded; "
                f"batch aborted before dispatch"))
        self.flush()
        if self.metrics is not None:
            # Join the exporter like every other worker (f16race
            # dogfood): its ThreadingHTTPServer thread must not outlive
            # the drained service holding the port and scraping
            # callbacks into torn-down state.
            self.metrics.stop()
            self.metrics = None
        self._started = False
        acct = {
            "phase": "complete" if clean else "abort",
            "completed": self.latency.snapshot()["count"] - done_before,
            "rejected": len(queued),
            "aborted": aborted,
            "wall_s": round(time.perf_counter() - t0, 3),
        }
        obs.event("drain", phase=acct["phase"],
                  completed=acct["completed"], rejected=acct["rejected"],
                  aborted=acct["aborted"])
        return acct

    def flush(self):
        """Flush durable serve state: the registry index, the AOT warm
        manifest (signatures computed WITHOUT compiling — the
        reload-warm contract's check value), and the obs manifest.
        Returns the manifest path (None for a rootless registry)."""
        manifest_path = None
        if getattr(self.registry, "root", None):
            self.registry.flush()
            manifest_path = os.path.join(self.registry.root, MANIFEST_FILE)
            self.store.flush_manifest(
                manifest_path, self.registry.models(), self.buckets)
        obs.manifest_update(
            verb="serve", serve_models=len(self.registry),
            serve_manifest=manifest_path)
        return manifest_path

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- client API ------------------------------------------------------

    def _admit(self, model_id, x, kind):
        if self.slo is not None and self.slo.shedding:
            # Bounded-admission rejection: while the burn-rate breach
            # stands, new work is refused at the door — the queue must
            # never grow into the latency it is supposed to cure.
            # Retriable: nothing was queued or dispatched.
            self.slo.record_shed()
            raise RetriableRejection(
                "shedding load (SLO burn-rate breach); retry later")
        if kind not in KINDS:
            raise RequestRejected(f"unknown kind: {kind!r} (want {KINDS})")
        model = self.registry.get(model_id)
        if model is None:
            raise RequestRejected(f"model not registered: {model_id}")
        if model_id in self.batcher.quarantined:
            raise RequestRejected(
                f"model quarantined: {model_id} "
                f"[{self.batcher.quarantined[model_id]['fault_class']}]")
        x = np.asarray(x, dtype=np.float32)
        if x.ndim == 1:
            x = x[None, :]
        n_cols = len(model.cols)
        if x.ndim != 2:
            raise RequestRejected(f"want [n, features], got {x.shape}")
        if x.shape[1] != n_cols:
            if x.shape[1] > max(model.cols):
                x = x[:, list(model.cols)]  # full feature rows: select
            else:
                raise RequestRejected(
                    f"feature width {x.shape[1]} matches neither the "
                    f"config's {n_cols} columns nor the full set")
        if not 1 <= x.shape[0] <= self.buckets[-1]:
            raise RequestRejected(
                f"batch rows {x.shape[0]} outside [1, {self.buckets[-1]}]"
                " (split client-side)")
        return model, x

    def submit(self, model_id, x, kind="predict", trace_parent=None):
        """Admit one request; returns the :class:`ScoreRequest` future.
        A trace context is minted here (F16_TRACE_SAMPLE) and rides the
        request through the batcher to the response. ``trace_parent`` is
        the cross-process context a fleet worker received on the wire
        (ISSUE 19): when present the request ADOPTS the router's trace
        id instead of flipping a second sampling coin, so its spans nest
        under the router's span in the fleet-merged render."""
        _, x = self._admit(model_id, x, kind)
        trace = (obs.adopt_trace(trace_parent) if trace_parent
                 else obs.mint_trace())
        return self.requests.submit(
            ScoreRequest(model_id, x, kind=kind, trace=trace))

    def score(self, model_id, x, kind="predict", timeout=None):
        """Synchronous submit+result."""
        return self.submit(model_id, x, kind=kind).result(timeout)

    def stats(self):
        snap = self.latency.snapshot()
        return {
            "models": self.registry.ids(),
            "requests": snap["count"],
            "p50_ms": snap["p50_ms"],
            "p99_ms": snap["p99_ms"],
            "queue_depth": self.requests.depth(),
            "quarantined": dict(self.batcher.quarantined),
        }
