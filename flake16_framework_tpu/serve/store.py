"""AOT executable store: the serving layer's pre-compiled programs.

One :class:`~flake16_framework_tpu.obs.aot.AotExecutableCache` per kind
(predict / SHAP-xla / SHAP-pallas), **shared across every registered
model** — the compiled programs take the forest, mu and W as runtime
arguments, so models with equal artifact shapes dispatch through the
same executable and the compile bill is paid once per (shape, bucket),
not once per model. The caches are constructed with
``gate_on_telemetry=False``: a service must hit its compiled programs
whether or not F16_TELEMETRY is set.

The preprocessing affine is folded into the device program
(``transform(x, mu, W)`` before the forest walk), so a request carries
raw selected-column features and the padded batch crosses to the device
exactly once. SHAP values are w.r.t. the transformed coordinates — the
same convention the study's explain stage uses.

Failover wiring: the pallas SHAP arm exists only on TPU; its warm
failure at service start marks the resilience ladder's pallas rung
broken (the service degrades to the always-warmed xla arm rather than
refusing to start), and a call-time pallas fault marks the rung broken
then re-raises so the dispatch guard's retry lands on xla — the
pallas->xla degradation ladder as the failover path (ISSUE 6).
"""

import hashlib
import json

import jax

from flake16_framework_tpu.obs import aot as _aot
from flake16_framework_tpu.ops import trees
from flake16_framework_tpu.ops import treeshap
from flake16_framework_tpu.ops.preprocess import transform
from flake16_framework_tpu.resilience import ladder

KINDS = ("predict", "shap")

MANIFEST_FILE = "aot_manifest.json"
MANIFEST_SCHEMA = "flake16-serve-aot-manifest-v1"


def signature_digest(sig):
    """Short stable digest of one executable-dispatch signature — the
    JSON-able form the warm manifest stores."""
    return hashlib.sha1(repr(sig).encode()).hexdigest()[:16]


def _predict_raw(forest, mu, wmat, x):
    return trees.predict_proba(forest, transform(x, mu, wmat))


def _shap_xla_raw(forest, mu, wmat, x, *, depth):
    return treeshap._xla_forest_shap(forest, transform(x, mu, wmat),
                                     depth=depth)


def _shap_pallas_raw(forest, mu, wmat, x, *, depth):
    # _pallas_graph_shap is the TRACEABLE pallas program (the work-item
    # kernel on the in-graph single-bucket layout); the host-packed
    # _pallas_forest_shap driver cannot live inside an AOT executable.
    return treeshap._pallas_graph_shap(forest, transform(x, mu, wmat),
                                       depth=depth, interpret=False)


class ExecutableStore:
    """Pre-compiled predict + SHAP executables for a registry's models.

    ``donate`` is the donated-argument index tuple for the padded input
    buffer (position 3 = x). The batcher pads every request batch into a
    fresh buffer it never reads back, so donation is sound; it defaults
    off on CPU, where XLA ignores donation with a warning per compile.
    """

    def __init__(self, registry, *, donate=None):
        self.registry = registry
        backend = jax.default_backend()
        if donate is None:
            donate = () if backend == "cpu" else (3,)
        self._predict = _aot.AotExecutableCache(
            jax.jit(_predict_raw, donate_argnums=donate),
            "serve.predict", gate_on_telemetry=False)
        self._shap_xla = _aot.AotExecutableCache(
            jax.jit(_shap_xla_raw, static_argnames=("depth",),
                    donate_argnums=donate),
            "serve.shap_xla", gate_on_telemetry=False)
        self._shap_pallas = None
        if backend == "tpu":
            self._shap_pallas = _aot.AotExecutableCache(
                jax.jit(_shap_pallas_raw, static_argnames=("depth",),
                        donate_argnums=donate),
                "serve.shap_pallas", gate_on_telemetry=False)

    # -- internals -------------------------------------------------------

    def _args(self, model, x):
        return (model.forest, model.mu, model.wmat, x)

    def _shap_cache(self):
        """The SHAP arm current ladder state selects: pallas when present
        and not marked broken, else the always-warmed xla fallback."""
        if (self._shap_pallas is not None
                and not ladder.state().pallas_broken):
            return self._shap_pallas
        return self._shap_xla

    # -- warm / signatures ----------------------------------------------

    def warm(self, model, bucket_sizes):
        """Pre-compile every (kind, bucket) executable for one model.
        Returns {(kind, bucket): signature}. A pallas warm failure marks
        the ladder's pallas rung broken and the service serves the xla
        arm — degrade, don't refuse to start. xla compile errors
        propagate (an unservable model must fail at start, not at the
        first request)."""
        import numpy as np

        sigs = {}
        n_cols = len(model.cols)
        for bucket in bucket_sizes:
            x = np.zeros((bucket, n_cols), dtype=np.float32)
            sigs[("predict", bucket)] = self._predict.warm(
                *self._args(model, x))
            sigs[("shap", bucket)] = self._shap_xla.warm(
                *self._args(model, x), depth=model.depth)
            if (self._shap_pallas is not None
                    and not ladder.state().pallas_broken):
                try:
                    self._shap_pallas.warm(*self._args(model, x),
                                           depth=model.depth)
                except Exception as e:
                    ladder.mark_pallas_broken(e)
        return sigs

    def signatures(self, model, bucket):
        """The dispatch keys one model produces at one bucket, computed
        WITHOUT compiling — the registry round-trip contract is checked
        against these (register -> persist -> reload -> identical
        executable signature)."""
        import numpy as np

        x = np.zeros((bucket, len(model.cols)), dtype=np.float32)
        return {
            "predict": self._predict.signature(self._args(model, x), {}),
            "shap": self._shap_xla.signature(
                self._args(model, x), {"depth": model.depth}),
        }

    def warm_manifest(self, models, buckets):
        """{model_id: {"kind@bucket": digest}} over every registered
        (kind, bucket) pair, computed from :meth:`signatures` WITHOUT
        compiling. Equal manifests before a drain and after a reload
        mean the reloaded service dispatches through the very
        executables the drained one warmed — the reload-warm contract's
        check value (ISSUE 11b)."""
        out = {}
        for model in models:
            entry = {}
            for bucket in buckets:
                sigs = self.signatures(model, bucket)
                for kind in KINDS:
                    entry[f"{kind}@{int(bucket)}"] = signature_digest(
                        sigs[kind])
            out[model.model_id] = entry
        return out

    def flush_manifest(self, path, models, buckets):
        """Atomically write the warm manifest JSON — the drain path's
        AOT-store flush. Returns the manifest dict."""
        from flake16_framework_tpu.utils.atomic import atomic_write

        manifest = {
            "schema": MANIFEST_SCHEMA,
            "backend": jax.default_backend(),
            "buckets": [int(b) for b in buckets],
            "models": self.warm_manifest(models, buckets),
        }
        with atomic_write(path, "w") as fd:
            json.dump(manifest, fd, indent=1, sort_keys=True)
        return manifest

    def audit_handles(self, *, n_trees, max_nodes, n_cols, bucket, depth):
        """{entry name: (traceable fn, abstract args, static kwargs)} for
        every serving executable — the f16audit trace surface
        (analysis/rules_ir.serve_entries). Uses the caches'
        ``traceable()`` handles so the audit never touches the dispatch
        census, and abstract (ShapeDtypeStruct) artifact shapes so no
        registry, buffer, or compile is needed. The pallas arm is
        included whenever the cache exists (TPU); on CPU the xla arm IS
        the served program, and the pallas kernel body is audited via
        its interpret-mode entry (rules_ir's shap.pallas)."""
        from flake16_framework_tpu.analysis import ir

        forest = ir.abstract_forest(n_trees, max_nodes)
        S = jax.ShapeDtypeStruct
        mu = S((n_cols,), jax.numpy.float32)
        wmat = S((n_cols, n_cols), jax.numpy.float32)
        x = S((bucket, n_cols), jax.numpy.float32)
        args = (forest, mu, wmat, x)
        out = {
            "serve.predict": (self._predict.traceable()[0], args, {}),
            "serve.shap_xla": (self._shap_xla.traceable()[0], args,
                               {"depth": depth}),
        }
        if self._shap_pallas is not None:
            out["serve.shap_pallas"] = (
                self._shap_pallas.traceable()[0], args, {"depth": depth})
        return out

    # -- dispatch --------------------------------------------------------

    def call(self, model, kind, x):
        """Dispatch one padded batch through the pre-compiled executable
        for ``kind``. Called from inside the batcher's guard thunk — a
        pallas fault marks the rung broken and re-raises so the guard's
        retry degrades to xla."""
        if kind == "predict":
            return self._predict(*self._args(model, x))
        if kind != "shap":
            raise ValueError(f"unknown serve kind: {kind!r}")
        cache = self._shap_cache()
        if cache is self._shap_pallas:
            try:
                return cache(*self._args(model, x), depth=model.depth)
            except Exception as e:
                ladder.mark_pallas_broken(e)
                raise
        return cache(*self._args(model, x), depth=model.depth)
