"""Async request queue: submit -> future, with bounded depth.

The queue is the service's only admission point. ``submit`` either
accepts a request (returning it — the request doubles as its own
future: ``result()`` blocks on completion) or raises
:class:`RequestRejected` immediately when the queue is full or closed —
bounded memory and a fast-fail signal under overload, never silent
buffering.

``take_batch`` is the microbatcher's side: it blocks for the first
request, then greedily drains FIFO-ordered requests for the SAME
(model, kind) up to the row budget. There is no artificial gather
delay — microbatching emerges from dispatch backpressure (while the
bounded in-flight dispatches are busy, the queue accumulates, and the
next ``take_batch`` coalesces what arrived).

This module is serve hot-path scope for f16lint's J601 rule: nothing
here may block on a device->host transfer.
"""

import threading
import time


class ServeError(RuntimeError):
    """Base class for scoring-service errors. ``retriable`` is the
    client contract: True means nothing was dispatched on the request's
    behalf, so resubmitting (to this or a replacement service) is safe
    and expected; False means the same request would fail again."""

    retriable = False


class RequestRejected(ServeError):
    """Request refused at admission (queue full/closed, unknown or
    quarantined model, oversize batch)."""


class RetriableRejection(RequestRejected):
    """Request refused because the service is DRAINING (e.g. SIGTERM
    landed): it was queued but never handed to a dispatcher, so the
    client may safely resubmit to the restarted or replacement
    service. The drain path fails every unstarted request with this —
    never a silent drop."""

    retriable = True


class ScoreRequest:
    """One scoring request and its completion future. ``trace`` is the
    request's distributed-trace context (``obs.mint_trace()``: trace_id/
    span_id dict, or None when unsampled/off) — minted at submit and
    carried queue→batcher→dispatch so the batcher can stamp per-request
    lanes and record batch fan-in as span links."""

    __slots__ = ("kind", "model_id", "x", "n", "t_submit", "trace",
                 "_done", "_out", "_exc")

    def __init__(self, model_id, x, kind="predict", trace=None):
        self.model_id = model_id
        self.x = x
        self.n = int(x.shape[0])
        self.kind = kind
        self.t_submit = time.perf_counter()
        self.trace = trace
        self._done = threading.Event()
        self._out = None
        self._exc = None

    def _complete(self, out):
        self._out = out
        self._done.set()

    def _fail(self, exc):
        self._exc = exc
        self._done.set()

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block until the dispatch completes; re-raises the dispatch's
        failure (e.g. resilience.DispatchAbandoned after the guard
        exhausted its ladder)."""
        if not self._done.wait(timeout):
            raise ServeError(
                f"request not completed within {timeout}s "
                f"({self.model_id}/{self.kind})")
        if self._exc is not None:
            raise self._exc
        return self._out


class RequestQueue:
    """Bounded FIFO of :class:`ScoreRequest` with condition-variable
    handoff to the batcher's collector thread."""

    def __init__(self, maxsize=256):
        self.maxsize = int(maxsize)
        self._items = []
        self._cond = threading.Condition()
        self._closed = False

    def submit(self, request):
        with self._cond:
            if self._closed:
                # Closed means draining/stopped: nothing was dispatched,
                # so the rejection is retriable against a replacement.
                raise RetriableRejection(
                    "queue closed (draining); resubmit to the "
                    "replacement service")
            if len(self._items) >= self.maxsize:
                raise RequestRejected(
                    f"queue full ({self.maxsize} requests)")
            self._items.append(request)
            self._cond.notify()
        return request

    def take_batch(self, max_rows, wait_s=0.05):
        """Wait up to ``wait_s`` for a first request, then greedily take
        same-(model, kind) FIFO requests while total rows fit in
        ``max_rows``. Returns a (possibly empty) list; empty means the
        wait timed out (the collector loop re-checks for shutdown)."""
        with self._cond:
            if not self._items:
                self._cond.wait(wait_s)
            if not self._items:
                return []
            head = self._items[0]
            batch, rows, keep = [], 0, []
            for req in self._items:
                if (req.model_id == head.model_id
                        and req.kind == head.kind
                        and rows + req.n <= max_rows):
                    batch.append(req)
                    rows += req.n
                else:
                    keep.append(req)
            self._items = keep
            return batch

    def depth(self):
        with self._cond:
            return len(self._items)

    def close(self):
        """Stop admitting; queued requests still drain."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def drain_pending(self):
        """Pop and return every queued-but-uncollected request. The
        drain path calls this right after ``close()`` and fails each
        with :class:`RetriableRejection` — these were never dispatched,
        so the rejection is the retry signal, not an error."""
        with self._cond:
            items, self._items = self._items, []
            return items
