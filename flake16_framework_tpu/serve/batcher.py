"""Shape-bucketed microbatcher: the serving layer's dispatch engine.

One collector thread drains the request queue (coalescing FIFO
same-(model, kind) requests), pads each coalesced batch to the smallest
registered bucket shape, and hands it to a bounded pool of dispatcher
threads through a ``maxsize=max_inflight`` handoff queue — the handoff
blocking IS the backpressure that lets the request queue accumulate and
the next coalesce grow. Every dispatch goes through the resilience
guard (retry ladder, fault telemetry); a dispatch the guard abandons
quarantines the model, and the pallas->xla rung (store.call) plus the
ladder's cpu-fallback device context are the failover path.

Hot-path discipline (f16lint J601 scope): the ONLY device->host
transfer in this module is the single ``np.asarray`` on a completed
microbatch result — one crossing amortized over the batch's requests.
Everything else stays on host-side numpy or device values.
"""

import queue as _stdqueue
import threading
import time

import numpy as np

from flake16_framework_tpu import obs
from flake16_framework_tpu.resilience import guard as _guard
from flake16_framework_tpu.resilience import ladder as _ladder
from flake16_framework_tpu.serve.queue import ServeError


class Microbatcher:
    """Collector + bounded dispatcher pool between a
    :class:`~flake16_framework_tpu.serve.queue.RequestQueue` and an
    :class:`~flake16_framework_tpu.serve.store.ExecutableStore`."""

    def __init__(self, store, requests, *, buckets=(8, 32, 128),
                 max_inflight=2, guard=None, stats=None, monitor=None):
        self.store = store
        self.requests = requests
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.max_rows = self.buckets[-1]
        self.guard = guard if guard is not None else _guard.default_guard()
        self.stats = stats
        self.monitor = monitor  # obs.slo.SLOMonitor (None = no SLO loop)
        self.quarantined = {}
        # Guards quarantined writes: every dispatcher-pool worker can
        # quarantine on an abandoned dispatch (f16race C101). Admission
        # reads stay lock-free — a stale miss admits one request that
        # fails with the same DispatchAbandoned, which is benign.
        self._quarantine_lock = threading.Lock()
        self.inflight = 0  # dispatches currently inside _run_batch
        self._inflight_lock = threading.Lock()
        self._handoff = _stdqueue.Queue(maxsize=int(max_inflight))
        self._stop = threading.Event()
        self._threads = []
        self._max_inflight = int(max_inflight)

    # -- lifecycle -------------------------------------------------------

    def start(self):
        self._stop.clear()
        self._threads = [threading.Thread(
            target=self._collect, name="serve-collector", daemon=True)]
        self._threads += [threading.Thread(
            target=self._dispatch_loop, name=f"serve-dispatch-{i}",
            daemon=True) for i in range(self._max_inflight)]
        for t in self._threads:
            t.start()

    def stop(self, timeout=5.0):
        """Stop collecting; in-flight and handed-off batches drain.
        Returns True when every worker thread exited within ``timeout``
        (the shared deadline, not per-thread) — the drain path
        escalates to :meth:`abort_pending` on False."""
        self._stop.set()
        deadline = time.monotonic() + timeout
        clean = True
        for t in self._threads:
            t.join(max(0.0, deadline - time.monotonic()))
            clean = clean and not t.is_alive()
        self._threads = []
        return clean

    def abort_pending(self, exc):
        """Fail every handed-off-but-unstarted batch with ``exc`` and
        return the request count — the drain deadline's
        checkpoint-and-abort escalation. A request wedged INSIDE a
        dispatch belongs to its (daemon) worker and is not reclaimed
        here; its future completes or fails from the guard."""
        n = 0
        while True:
            try:
                batch = self._handoff.get_nowait()
            except _stdqueue.Empty:
                return n
            for r in batch:
                r._fail(exc)
                n += 1
            self._handoff.task_done()

    # -- threads ---------------------------------------------------------

    def _collect(self):
        while not self._stop.is_set():
            batch = self.requests.take_batch(self.max_rows, wait_s=0.05)
            if batch:
                self._handoff.put(batch)

    def _dispatch_loop(self):
        while True:
            try:
                batch = self._handoff.get(timeout=0.05)
            except _stdqueue.Empty:
                if self._stop.is_set():
                    return
                continue
            with self._inflight_lock:
                self.inflight += 1
            try:
                self._run_batch(batch)
            finally:
                with self._inflight_lock:
                    self.inflight -= 1
                self._handoff.task_done()

    # -- dispatch --------------------------------------------------------

    def _bucket_for(self, rows):
        for b in self.buckets:
            if rows <= b:
                return b
        return self.buckets[-1]

    def _fail_batch(self, batch, exc):
        for r in batch:
            r._fail(exc)
        if self.monitor is not None:
            for _ in batch:
                self.monitor.observe(error=True)
            self.monitor.evaluate()

    def _run_batch(self, batch):
        t_start = time.perf_counter()
        wall_start = time.time()
        req0 = batch[0]
        model = self.store.registry.get(req0.model_id)
        if model is None:
            self._fail_batch(batch, ServeError(
                f"model not registered: {req0.model_id}"))
            return
        if req0.model_id in self.quarantined:
            self._fail_batch(batch, ServeError(
                f"model quarantined: {req0.model_id} "
                f"[{self.quarantined[req0.model_id]['fault_class']}]"))
            return

        rows = sum(r.n for r in batch)
        bucket = self._bucket_for(rows)
        xpad = np.zeros((bucket, len(model.cols)), dtype=np.float32)
        off = 0
        for r in batch:
            xpad[off:off + r.n] = r.x
            off += r.n

        def thunk():
            with _ladder.device_context():
                return self.store.call(model, req0.kind, xpad)

        # Batch fan-in as span links: the coalesced requests' trace ids
        # ride the dispatch span, joining each sampled request's lane to
        # the microbatch that actually carried it.
        links = [r.trace["trace_id"] for r in batch if r.trace]
        span_fields = {"rows": rows, "bucket": bucket,
                       "coalesced": len(batch)}
        if links:
            span_fields["links"] = links
        try:
            with obs.span("serve.dispatch",
                          key=f"{req0.model_id}/{req0.kind}",
                          **span_fields):
                with obs.xprof_trace(f"serve-{req0.kind}"):
                    out = self.guard.call(
                        thunk, config_index=model.config_index,
                        label=f"serve:{req0.model_id}:{req0.kind}")
        except Exception as e:
            if isinstance(e, _guard.DispatchAbandoned):
                with self._quarantine_lock:
                    self.quarantined[req0.model_id] = {
                        "fault_class": e.fault_class,
                        "attempts": len(e.attempts),
                        "kind": req0.kind,
                    }
            self._fail_batch(batch, e)
            return

        host = np.asarray(out)  # f16lint: disable=J601
        t_done = time.perf_counter()
        off = 0
        for r in batch:
            r._complete(host[off:off + r.n].copy())
            off += r.n
            latency_ms = (t_done - r.t_submit) * 1000.0
            if self.stats is not None:
                self.stats.record(latency_ms)
            if self.monitor is not None:
                self.monitor.observe(latency_ms=latency_ms)
            if r.trace:
                # Per-request lanes (trace renderer): the queue leg ends
                # at dispatch start; the full request leg ends now —
                # start = ts - wall in both, so the lane reads
                # submit→dispatch→response without clock gymnastics.
                # An adopted cross-process context carries parent_id
                # (the router's span) — the fleet-merged render stitches
                # this process's lane to the router's on it.
                tctx = {"trace_id": r.trace["trace_id"],
                        "span_id": r.trace["span_id"]}
                if r.trace.get("parent_id"):
                    tctx["parent_id"] = r.trace["parent_id"]
                obs.event("span", name="serve.request.queue",
                          wall_s=round(t_start - r.t_submit, 6),
                          cold=False, ts=round(wall_start, 4),
                          model_id=r.model_id, req_kind=r.kind, **tctx)
                obs.event("span", name="serve.request",
                          wall_s=round(t_done - r.t_submit, 6),
                          cold=False,
                          model_id=r.model_id, req_kind=r.kind, rows=r.n,
                          coalesced=len(batch), **tctx)
        obs.counter_add("serve.requests", len(batch))
        obs.gauge("serve.queue_depth", self.requests.depth())
        obs.gauge("serve.inflight", self.inflight)
        if self.stats is not None:
            snap = self.stats.snapshot()
            obs.gauge("serve.p50_ms", snap["p50_ms"])
            obs.gauge("serve.p99_ms", snap["p99_ms"])
        if self.monitor is not None:
            self.monitor.evaluate()
