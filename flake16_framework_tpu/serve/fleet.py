"""Serving fleet: N replicated worker processes behind one router
(ISSUE 18 tentpole; ROADMAP item 2, "one process → a replicated
fleet").

Two halves live here — the WORKER (``worker_main`` + ``WorkerServer``:
a child process running the existing :class:`ScoringService` against
the shared on-disk model registry + AOT artifact store, speaking the
serve/wire.py frame protocol on an AF_UNIX socket) and the FLEET
MANAGER (``Fleet`` + ``WorkerHandle``: the parent that spawns workers,
watches them, dumps a dead worker's flight ring, and respawns within a
restart budget). The routing brain — health gating, hedging, failover,
rolling restarts — is serve/router.py; the fleet only keeps processes
alive and findable.

Worker lifecycle: spawn ``python -m flake16_framework_tpu serve
--worker --socket P --registry DIR`` with ``F16_FLEET_WORKER=<i>`` in
the environment → the worker LOADS the persisted registry (no fitting;
the shared on-disk artifacts + the persistent XLA compile cache are
what make a W-worker fleet start W compiles cheap, not W× the bill),
warms, listens, prints ``WORKER_READY``. Each router connection gets a
reader, a bounded waiter pool, and a heartbeat pusher
(``F16_FLEET_HEARTBEAT_S``) that streams the worker's queue-depth /
inflight / p50 / p99 / SLO burn gauges — the same per-worker health
the obs metrics exporter serves, delivered in-band so the router needs
no scrape loop.

Restart policy (supervisor.py's budget, fleet-shaped): a SIGNAL death
(rc < 0) counts against ``max_restarts`` and triggers a flight-ring
dump + respawn with fault-inject process/worker entries stripped (an
injected kill fires exactly once); a CLEAN exit (rc == 0, the drain
path — rolling restarts end workers this way) respawns for free; a
NONZERO exit marks the worker failed without respawn (a registry that
cannot load would otherwise crash-loop the budget away).

Chaos hooks: ``F16_FAULT_INJECT=<worker>:<request#>:worker-kill``
SIGKILLs the worker as the Nth score request arrives (requests in
flight — the router-failover drill); ``worker-stall`` freezes it
(heartbeats stop, accepted requests never answer) so health gating and
hedging have a deterministic straggler.
"""

import json
import os
import signal
import socket as _socket
import subprocess
import sys
import threading
import time

import queue as _stdqueue

from flake16_framework_tpu.serve import wire

# The worker's index within its fleet — set by the fleet manager in
# each child's environment; consulted by fault injection (worker
# entries address it) and by the flight recorder's ring-path
# uniquification (obs/flight.env_path appends ``.w<i>``).
WORKER_ENV = "F16_FLEET_WORKER"

# Heartbeat push interval, seconds (workers stream health in-band).
HEARTBEAT_ENV = "F16_FLEET_HEARTBEAT_S"
DEFAULT_HEARTBEAT_S = 0.25

WORKER_READY = "WORKER_READY"


def heartbeat_interval(environ=None):
    env = os.environ if environ is None else environ
    raw = env.get(HEARTBEAT_ENV, "")
    try:
        val = float(raw) if raw else DEFAULT_HEARTBEAT_S
    except ValueError:
        val = DEFAULT_HEARTBEAT_S
    return max(0.05, val)


def worker_index(environ=None):
    env = os.environ if environ is None else environ
    try:
        return int(env.get(WORKER_ENV, "") or 0)
    except ValueError:
        return 0


# ---------------------------------------------------------------------
# Worker half
# ---------------------------------------------------------------------


class WorkerServer:
    """One worker's socket front: accept router connections, decode
    frames, run ops against the wrapped :class:`ScoringService`, push
    heartbeats. ``serve_forever`` returns the drain accounting dict
    once a ``drain`` op lands (the worker then exits 0 — the fleet
    manager respawns a fresh process; a worker never un-drains)."""

    def __init__(self, service, socket_path, *, index=None,
                 heartbeat_s=None, environ=None, waiters=8):
        from flake16_framework_tpu.resilience import inject

        self.service = service
        self.socket_path = socket_path
        env = os.environ if environ is None else environ
        self.index = worker_index(env) if index is None else int(index)
        self.heartbeat_s = (heartbeat_interval(env) if heartbeat_s is None
                            else float(heartbeat_s))
        self._waiters = int(waiters)
        self._plan = inject.plan_from_env(env)
        self._score_no = 0
        self._score_lock = threading.Lock()
        self._stalled = threading.Event()
        self._drained = threading.Event()
        # drain accounting crosses threads: written by whichever conn
        # thread receives the drain op, read by serve_forever after
        # ``_drained`` fires — locked so a second (erroneous) drain op
        # cannot race the read.
        self._acct_lock = threading.Lock()
        self._drain_acct = None
        self._listener = None

    # -- chaos (worker fault-inject classes) -----------------------------

    def _inject_check(self):
        """Consult the fault plan before the next score request; deliver
        worker-kill/worker-stall when scheduled. Returns True when the
        request must be swallowed (stall)."""
        if self._plan is None:
            return self._stalled.is_set()
        with self._score_lock:
            self._score_no += 1
            n = self._score_no
        action = self._plan.worker_action(self.index, n)
        if action == "worker-kill":
            # The drill's deterministic crash: requests are in flight,
            # the socket closes with them unanswered — the router's
            # failover path owns every one of them now.
            os.kill(os.getpid(), signal.SIGKILL)
        elif action == "worker-stall":
            self._stalled.set()
        return self._stalled.is_set()

    # -- heartbeat -------------------------------------------------------

    def _hb_payload(self):
        snap = self.service.latency.snapshot()
        hb = {
            "ts": round(time.time(), 4),
            "worker": self.index,
            "pid": os.getpid(),
            "queue_depth": self.service.requests.depth(),
            "inflight": self.service.batcher.inflight,
            "requests": snap["count"],
            "p50_ms": snap["p50_ms"],
            "p99_ms": snap["p99_ms"],
            "quarantined": sorted(self.service.batcher.quarantined),
            "models": self.service.registry.ids(),
            "shedding": False,
        }
        if self.service.slo is not None:
            hb["shedding"] = self.service.slo.shedding
            hb["burn_fast"] = round(self.service.slo.burn_fast, 3)
            hb["burn_slow"] = round(self.service.slo.burn_slow, 3)
        return hb

    def _hb_loop(self, conn, send_lock, dead):
        while not dead.is_set() and not self._stalled.is_set() \
                and not self._drained.is_set():
            try:
                with send_lock:
                    wire.send_msg(conn, {"hb": self._hb_payload()})
            except OSError:
                return
            dead.wait(self.heartbeat_s)

    # -- per-connection machinery ----------------------------------------

    def _send_error(self, conn, send_lock, rid, exc):
        msg = {"id": rid, "ok": False, "error": str(exc),
               "retriable": bool(getattr(exc, "retriable", False)),
               "error_type": type(exc).__name__}
        with send_lock:
            wire.send_msg(conn, msg)

    def _waiter_loop(self, conn, send_lock, handoff, dead):
        """Block on score futures and ship responses — a bounded pool so
        the reader never blocks on a slow dispatch."""
        while not dead.is_set():
            try:
                rid, fut = handoff.get(timeout=0.1)
            except _stdqueue.Empty:
                continue
            try:
                try:
                    out = fut.result(timeout=120.0)
                except Exception as e:
                    if not self._stalled.is_set():
                        try:
                            self._send_error(conn, send_lock, rid, e)
                        except OSError:
                            return
                    continue
                if self._stalled.is_set():
                    continue  # a stalled worker never answers
                try:
                    with send_lock:
                        wire.send_msg(conn, {"id": rid, "ok": True,
                                             "out": out})
                except OSError:
                    return
            finally:
                handoff.task_done()

    def _handle_conn(self, conn):
        send_lock = threading.Lock()
        dead = threading.Event()
        handoff = _stdqueue.Queue()
        threads = [threading.Thread(
            target=self._hb_loop, args=(conn, send_lock, dead),
            name=f"fleet-w{self.index}-hb", daemon=True)]
        threads += [threading.Thread(
            target=self._waiter_loop, args=(conn, send_lock, handoff, dead),
            name=f"fleet-w{self.index}-wait{i}", daemon=True)
            for i in range(self._waiters)]
        for t in threads:
            t.start()
        try:
            while True:
                try:
                    msg = wire.recv_msg(conn)
                except wire.WireError:
                    return
                if msg is None or not isinstance(msg, dict):
                    return
                if "id" not in msg:
                    continue  # pushes flow worker->router only
                rid, op = msg["id"], msg.get("op")
                if op == "score":
                    if self._inject_check():
                        continue  # stalled: accepted, never answered
                    # Cross-process trace adoption (ISSUE 19): a frame
                    # carrying trace_id was sampled by the ROUTER — join
                    # its trace rather than re-flipping the local coin.
                    parent = None
                    if "trace_id" in msg:
                        parent = {"trace_id": msg["trace_id"],
                                  "parent_id": msg.get("parent_id")}
                    try:
                        fut = self.service.submit(
                            msg["model"], msg["x"],
                            kind=msg.get("kind", "predict"),
                            trace_parent=parent)
                    except Exception as e:
                        self._send_error(conn, send_lock, rid, e)
                        continue
                    handoff.put((rid, fut))
                elif op == "ping":
                    with send_lock:
                        wire.send_msg(conn, {"id": rid, "ok": True,
                                             "worker": self.index,
                                             "pid": os.getpid()})
                elif op == "stats":
                    stats = self.service.stats()
                    stats["quarantined"] = sorted(stats["quarantined"])
                    with send_lock:
                        wire.send_msg(conn, {"id": rid, "ok": True,
                                             "stats": stats})
                elif op == "drain":
                    acct = self.service.drain(
                        deadline_s=float(msg.get("deadline_s", 10.0)))
                    # Every submitted future has settled; give the
                    # waiter pool a bounded window to flush responses
                    # before the ack (an unflushed response would be
                    # re-dispatched by the router's failover path —
                    # correct but noisy).
                    flush_by = time.monotonic() + 5.0
                    while handoff.unfinished_tasks \
                            and time.monotonic() < flush_by:
                        time.sleep(0.01)
                    with self._acct_lock:
                        self._drain_acct = acct
                    with send_lock:
                        wire.send_msg(conn, {"id": rid, "ok": True,
                                             "acct": acct})
                    self._drained.set()
                    return
                else:
                    self._send_error(conn, send_lock, rid,
                                     ValueError(f"unknown op {op!r}"))
        except OSError:
            return
        finally:
            dead.set()
            try:
                conn.close()
            except OSError:
                pass

    def serve_forever(self):
        """Accept router connections until a drain op lands; returns the
        drain accounting dict (None when the listener died first)."""
        self._listener = wire.listen_unix(self.socket_path)
        self._listener.settimeout(0.25)
        conn_threads = []
        while not self._drained.is_set():
            try:
                conn, _ = self._listener.accept()
            except _socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._handle_conn, args=(conn,),
                                 name=f"fleet-w{self.index}-conn",
                                 daemon=True)
            t.start()
            conn_threads.append(t)
        try:
            self._listener.close()
            os.unlink(self.socket_path)
        except OSError:
            pass
        with self._acct_lock:
            return self._drain_acct


def worker_main(opts):
    """The ``serve --worker`` entry point: load the persisted registry
    (shared on-disk artifacts — no fitting in a worker), warm, listen,
    serve until drained. Exit 0 after a clean drain."""
    from flake16_framework_tpu import obs
    from flake16_framework_tpu.serve.registry import ModelRegistry
    from flake16_framework_tpu.serve.service import ScoringService

    if not opts.get("registry"):
        raise ValueError("serve --worker requires --registry DIR "
                         "(workers load persisted artifacts)")
    if not opts.get("socket"):
        raise ValueError("serve --worker requires --socket PATH")

    registry = ModelRegistry(opts["registry"])
    if not registry.load():
        raise ValueError(
            f"serve --worker: no loadable models under {opts['registry']}")

    slo_cfg = None
    if opts.get("slo"):
        # SLO only when asked: _parse defaults slo_p99_ms=50.0, so
        # keying on the objective value would arm every worker with a
        # 50 ms p99 — and one worker's failover-absorbed load spike
        # would shed the whole fleet.
        from flake16_framework_tpu.obs.slo import SLOConfig

        slo_cfg = SLOConfig(p99_ms=opts.get("slo_p99_ms") or 50.0)

    idx = worker_index()
    with ScoringService(registry, buckets=opts.get("buckets"),
                        slo=slo_cfg,
                        metrics_port=opts.get("metrics_port")) as svc:
        server = WorkerServer(svc, opts["socket"], index=idx)
        obs.manifest_update(verb="serve", fleet_worker=idx,
                            fleet_socket=opts["socket"])
        print(f"{WORKER_READY} {idx} pid={os.getpid()}", flush=True)
        acct = server.serve_forever()
    if acct is not None:
        print("WORKER_DRAINED " + json.dumps(acct), flush=True)
    return 0


# ---------------------------------------------------------------------
# Fleet manager half (parent process)
# ---------------------------------------------------------------------


class WorkerHandle:
    """One managed worker process: identity, spawn state, restart
    accounting. All mutation happens under the owning Fleet's lock."""

    __slots__ = ("index", "socket_path", "proc", "env", "log_path",
                 "restarts", "failed", "spawned")

    def __init__(self, index, socket_path, log_path):
        self.index = index
        self.socket_path = socket_path
        self.log_path = log_path
        self.proc = None
        self.env = None
        self.restarts = 0
        self.failed = False
        self.spawned = 0

    @property
    def pid(self):
        return self.proc.pid if self.proc is not None else None

    def alive(self):
        return self.proc is not None and self.proc.poll() is None


class Fleet:
    """Spawn + supervise N workers over one persisted registry. The
    router connects to ``socket_paths()``; the fleet keeps those
    sockets occupied (restart budget for signal deaths, free respawn
    after clean drain exits) and dumps a dead worker's flight ring
    before replacing it."""

    def __init__(self, registry_dir, n_workers, *, workdir,
                 buckets=None, max_restarts=3, slo_p99_ms=None,
                 env=None, python=None, ready_timeout_s=300.0):
        self.registry_dir = registry_dir
        self.n_workers = int(n_workers)
        self.workdir = workdir
        self.buckets = buckets
        self.max_restarts = int(max_restarts)
        self.slo_p99_ms = slo_p99_ms
        self.ready_timeout_s = float(ready_timeout_s)
        self._base_env = dict(os.environ if env is None else env)
        self._python = python or sys.executable
        self._lock = threading.Lock()
        self._stopping = False
        self.workers = []
        self._monitors = []
        os.makedirs(workdir, exist_ok=True)
        for i in range(self.n_workers):
            self.workers.append(WorkerHandle(
                i, os.path.join(workdir, f"worker{i}.sock"),
                os.path.join(workdir, f"worker{i}.log")))

    # -- spawn -----------------------------------------------------------

    def _worker_env(self, handle, *, strip_inject):
        from flake16_framework_tpu.resilience import inject

        env = dict(self._base_env)
        env[WORKER_ENV] = str(handle.index)
        # The child must import this package regardless of the parent's
        # cwd (an installed dist doesn't need it; a source checkout run
        # from elsewhere does).
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        existing = env.get("PYTHONPATH", "")
        if pkg_parent not in existing.split(os.pathsep):
            env["PYTHONPATH"] = (pkg_parent + os.pathsep + existing
                                 if existing else pkg_parent)
        if strip_inject and env.get(inject.ENV_VAR):
            stripped = inject.strip_process_entries(env[inject.ENV_VAR])
            if stripped:
                env[inject.ENV_VAR] = stripped
            else:
                env.pop(inject.ENV_VAR, None)
        return env

    def _argv(self, handle):
        argv = [self._python, "-m", "flake16_framework_tpu", "serve",
                "--worker", "--socket", handle.socket_path,
                "--registry", self.registry_dir]
        if self.buckets:
            argv += ["--buckets",
                     ",".join(str(b) for b in self.buckets)]
        if self.slo_p99_ms is not None:
            argv += ["--slo", "--slo-p99-ms", str(self.slo_p99_ms)]
        return argv

    def _spawn(self, handle, *, strip_inject):
        handle.env = self._worker_env(handle, strip_inject=strip_inject)
        log = open(handle.log_path, "ab")
        try:
            handle.proc = subprocess.Popen(
                self._argv(handle), stdout=log, stderr=log,
                env=handle.env)
        finally:
            log.close()
        handle.spawned += 1
        t = threading.Thread(target=self._monitor, args=(handle,),
                             name=f"fleet-mon-w{handle.index}",
                             daemon=True)
        t.start()
        self._monitors.append(t)

    def start(self):
        for handle in self.workers:
            self._spawn(handle, strip_inject=False)
        self.wait_ready()
        return self

    # -- readiness -------------------------------------------------------

    def _probe(self, handle):
        try:
            sock = wire.connect_unix(handle.socket_path, timeout=0.5)
            sock.close()
            return True
        except OSError:
            return False

    def wait_ready(self, indices=None, timeout_s=None):
        """Block until every (selected) worker's socket accepts — the
        warm bill is paid here, not at the first request. Raises on a
        worker that died before listening."""
        deadline = time.monotonic() + (timeout_s or self.ready_timeout_s)
        pending = list(indices if indices is not None
                       else range(self.n_workers))
        while pending:
            for i in list(pending):
                handle = self.workers[i]
                if self._probe(handle):
                    pending.remove(i)
                elif not handle.alive() and handle.failed:
                    raise RuntimeError(
                        f"fleet worker {i} failed before ready "
                        f"(rc={handle.proc.returncode}; see "
                        f"{handle.log_path})")
            if pending:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"fleet workers {pending} not ready within "
                        f"{timeout_s or self.ready_timeout_s}s")
                time.sleep(0.1)

    # -- supervision -----------------------------------------------------

    def flight_ring_path(self, handle):
        """The per-worker flight ring path (obs/flight.env_path with the
        worker's environment — the ``.w<i>`` uniquified form), or None
        when the ring is unarmed or unresolvable from the parent."""
        from flake16_framework_tpu.obs import flight

        return flight.env_path(environ=handle.env or self._base_env)

    def _dump_flight(self, handle):
        path = self.flight_ring_path(handle)
        if not path or not os.path.isfile(path):
            return
        from flake16_framework_tpu.obs import flight

        try:
            flight.dump(path)
        except (OSError, ValueError):
            pass  # a corrupt corpse ring must not block the respawn

    def _monitor(self, handle):
        proc = handle.proc
        rc = proc.wait()
        with self._lock:
            if self._stopping or proc is not handle.proc:
                return
            from flake16_framework_tpu import obs

            if rc < 0:
                # Signal death: dump the black box, spend the budget.
                self._dump_flight(handle)
                handle.restarts += 1
                if handle.restarts > self.max_restarts:
                    handle.failed = True
                    obs.event("fleet", action="budget-exhausted",
                              worker=handle.index, rc=rc,
                              restarts=handle.restarts)
                    return
                obs.event("fleet", action="restart", worker=handle.index,
                          rc=rc, restarts=handle.restarts)
                self._spawn(handle, strip_inject=True)
            elif rc == 0:
                # Clean drain exit (rolling restart): free respawn.
                if handle.spawned > 0:
                    obs.event("fleet", action="respawn-drained",
                              worker=handle.index)
                    self._spawn(handle, strip_inject=True)
            else:
                # A worker exiting nonzero could not load/serve the
                # registry — respawning would crash-loop the budget.
                handle.failed = True
                obs.event("fleet", action="failed", worker=handle.index,
                          rc=rc)

    # -- accessors / teardown --------------------------------------------

    def socket_paths(self):
        return [h.socket_path for h in self.workers]

    def pids(self):
        return [h.pid for h in self.workers]

    def stop(self, timeout_s=10.0):
        """Terminate every worker (SIGTERM → SIGKILL escalation). The
        zero-drop path is the router's ``rolling_restart``/drain — this
        is the unceremonious end-of-run teardown."""
        with self._lock:
            self._stopping = True
            procs = [h.proc for h in self.workers if h.alive()]
        for p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + timeout_s
        for p in procs:
            try:
                p.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                    p.wait(5.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        for h in self.workers:
            try:
                os.unlink(h.socket_path)
            except OSError:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
