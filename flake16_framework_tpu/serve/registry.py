"""Model registry: trained-config artifacts the scoring service serves.

A *registered model* is the trained artifact of one grid config — the
node-trimmed forest, the preprocessing affine (mu, W), the feature
columns, and the config's identity (key tuple + canonical 216-order
index, the same index the fault-injection plan addresses). Registration
reuses the SHAP stage's fit recipe exactly (pipeline.shap_for_config's
staged path: preprocess -> transform -> resample -> fit on the balanced
full set), so a served prediction is the same program the study's
explain stage ran.

Identity is the **artifact signature**: (config code, pytree structure,
per-leaf shape/dtype) of the (forest, mu, W) artifact — the same key
family ``obs.aot.AotExecutableCache.signature`` dispatches on, which is
what makes the round-trip contract testable: register -> persist ->
reload must yield an identical executable signature, i.e. the reloaded
model hits the very executables warmed before the save.

The sweep's scores ledger is the artifact *source*: ``configs_from_
ledger`` reads a (partial or complete) ``scores.pkl`` and returns its
config keys in canonical grid order, so "serve what the sweep scored"
is one call. Persistence is one pickle per model under the registry
root plus a ``registry.json`` index (``utils.atomic_write``, like every
other durable-artifact writer in this repo).
"""

import hashlib
import json
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from flake16_framework_tpu import config as cfg
from flake16_framework_tpu.ops import trees
from flake16_framework_tpu.ops.preprocess import fit_preprocess, transform
from flake16_framework_tpu.ops.resample import resample
from flake16_framework_tpu.utils.atomic import atomic_write

REGISTRY_SCHEMA = "flake16-serve-registry-v1"
INDEX_FILE = "registry.json"


def model_id_for(config_keys):
    """Stable, filesystem-safe id for a config's artifact slot (the key
    tuple is unique per grid config, so no hash suffix is needed)."""
    return "-".join("".join(ch for ch in k.lower() if ch.isalnum())
                    for k in config_keys)


def config_index_for(config_keys):
    """The config's index in the canonical 216-order
    (config.iter_config_keys) — the address fault-injection plans and the
    sweep's per-config RNG both use. None for an off-grid tuple."""
    for i, keys in enumerate(cfg.iter_config_keys()):
        if tuple(keys) == tuple(config_keys):
            return i
    return None


class RegisteredModel:
    """One trained-config artifact: everything a serve dispatch needs."""

    __slots__ = ("model_id", "config_keys", "config_index", "forest",
                 "mu", "wmat", "cols", "depth", "seed", "max_depth")

    def __init__(self, *, model_id, config_keys, config_index, forest,
                 mu, wmat, cols, depth, seed, max_depth):
        self.model_id = model_id
        self.config_keys = tuple(config_keys)
        self.config_index = config_index
        self.forest = forest
        self.mu = mu
        self.wmat = wmat
        self.cols = tuple(cols)
        self.depth = int(depth)
        self.seed = int(seed)
        self.max_depth = int(max_depth)


def artifact_signature(model):
    """(config code, tree structure, per-leaf shape/dtype) of the served
    artifact — the registry's identity key. Deterministic across
    processes for the same trained shapes; the executable-store dispatch
    key is derived from the same leaves, so equal artifact signatures
    imply identical executable signatures at every registered batch
    shape (tests/test_serve.py pins the round trip)."""
    art = (model.forest, model.mu, model.wmat)
    leaves = jax.tree_util.tree_leaves(art)
    return (
        "/".join(model.config_keys),
        str(jax.tree_util.tree_structure(art)),
        tuple((tuple(leaf.shape), str(leaf.dtype)) for leaf in leaves),
    )


def signature_digest(model):
    return hashlib.sha1(repr(artifact_signature(model)).encode()) \
        .hexdigest()[:16]


def configs_from_ledger(scores_pkl):
    """Config key tuples present in a sweep scores ledger, in canonical
    grid order — the artifact source for "serve what the sweep scored"."""
    with open(scores_pkl, "rb") as fd:
        ledger = pickle.load(fd)
    if not isinstance(ledger, dict):
        raise ValueError(f"{scores_pkl}: not a scores ledger (want a dict)")
    present = {tuple(k) for k in ledger}
    return [keys for keys in cfg.iter_config_keys() if keys in present]


def fit_model(config_keys, feats, labels_raw, *, max_depth=48,
              tree_overrides=None, seed=0):
    """Train one config's artifact — the SHAP stage's fit recipe
    (pipeline.shap_for_config staged path), then node-trim the forest
    once so the artifact signature is stable and the SHAP executable's
    leaf-slot workspace is sized to the grown trees, not the fit-time
    worst-case bound."""
    fl, cols, prep, bal, spec = cfg.resolve_config(config_keys)
    if tree_overrides and spec.name in tree_overrides:
        spec = type(spec)(spec.name, tree_overrides[spec.name],
                          spec.bootstrap, spec.random_splits,
                          spec.sqrt_features)

    x = np.asarray(feats[:, list(cols)], dtype=np.float32)
    y = np.asarray(labels_raw) == fl
    n = x.shape[0]

    key = jax.random.PRNGKey(seed)
    mu, wmat = jax.jit(fit_preprocess)(x, prep)
    xp = transform(x, mu, wmat)
    kb, kf = jax.random.split(key)
    xs, ys, ws = resample(xp, y, np.ones(n, np.float32), bal, kb, 2 * n)
    fit_kw = dict(
        n_trees=spec.n_trees, bootstrap=spec.bootstrap,
        random_splits=spec.random_splits, sqrt_features=spec.sqrt_features,
        max_depth=max_depth, max_nodes=4 * n,
    )
    # Grower tier follows the sweep's rule (hist for ensembles unless
    # F16_ENSEMBLE_GROWER=exact; single-tree DT stays exact) so served
    # artifacts match swept models.
    forest = (trees.fit_forest_hist if trees.hist_tier_default(spec.n_trees)
              else trees.fit_forest)(xs, ys, ws, kf, **fit_kw)

    # One registration-time host sync (cold path, never per request):
    # trim to the grown node count rounded to 128 slots, exactly like
    # treeshap.forest_shap_class0's top-level trim.
    m = forest.feature.shape[-1]
    n_used = int(jax.device_get(jnp.max(forest.n_nodes)))
    m_trim = min(m, max(128, -(-n_used // 128) * 128))
    if m_trim < m:
        forest = trees.trim_nodes(forest, m_trim)

    return RegisteredModel(
        model_id=model_id_for(config_keys), config_keys=config_keys,
        config_index=config_index_for(config_keys), forest=forest,
        mu=mu, wmat=wmat, cols=cols, depth=int(forest.max_depth),
        seed=seed, max_depth=max_depth,
    )


class ModelRegistry:
    """The registry: in-memory map + on-disk artifact store under
    ``root``. All writes are atomic replaces; ``load()`` rebuilds the
    map from disk (service restart)."""

    def __init__(self, root):
        self.root = root
        self._models = {}

    # -- access ----------------------------------------------------------

    def get(self, model_id):
        return self._models.get(model_id)

    def ids(self):
        return sorted(self._models)

    def models(self):
        return [self._models[m] for m in self.ids()]

    def __len__(self):
        return len(self._models)

    def __contains__(self, model_id):
        return model_id in self._models

    # -- registration ----------------------------------------------------

    def register(self, model, persist=True):
        self._models[model.model_id] = model
        if persist:
            self._persist(model)
        return model

    def fit_and_register(self, config_keys, feats, labels_raw, *,
                         max_depth=48, tree_overrides=None, seed=0,
                         persist=True):
        model = fit_model(config_keys, feats, labels_raw,
                          max_depth=max_depth,
                          tree_overrides=tree_overrides, seed=seed)
        return self.register(model, persist=persist)

    def register_from_ledger(self, scores_pkl, feats, labels_raw, *,
                             limit=None, **fit_kw):
        """Fit + register every config the sweep's scores ledger holds
        (canonical order; ``limit`` bounds the count for bounded service
        start)."""
        configs = configs_from_ledger(scores_pkl)
        if limit is not None:
            configs = configs[:limit]
        return [self.fit_and_register(keys, feats, labels_raw, **fit_kw)
                for keys in configs]

    # -- persistence -----------------------------------------------------

    def _persist(self, model):
        os.makedirs(self.root, exist_ok=True)
        path = os.path.join(self.root, f"{model.model_id}.pkl")
        record = {
            "schema": REGISTRY_SCHEMA,
            "config_keys": list(model.config_keys),
            "config_index": model.config_index,
            "cols": list(model.cols),
            "depth": model.depth,
            "seed": model.seed,
            "max_depth": model.max_depth,
            "forest": {f: np.asarray(getattr(model.forest, f))
                       for f in model.forest._fields},
            "mu": np.asarray(model.mu),
            "wmat": np.asarray(model.wmat),
        }
        with atomic_write(path, "wb") as fd:
            pickle.dump(record, fd)
        self._write_index()

    def _write_index(self):
        index = {
            "schema": REGISTRY_SCHEMA,
            "models": {
                m.model_id: {
                    "config": "/".join(m.config_keys),
                    "config_index": m.config_index,
                    "file": f"{m.model_id}.pkl",
                    "signature_sha1": signature_digest(m),
                } for m in self.models()
            },
        }
        path = os.path.join(self.root, INDEX_FILE)
        with atomic_write(path, "w") as fd:
            json.dump(index, fd, indent=1)

    def flush(self):
        """Re-write the on-disk index from the in-memory map — the
        drain path's registry flush. Safe on an empty registry."""
        os.makedirs(self.root, exist_ok=True)
        self._write_index()

    def load(self):
        """Rebuild the in-memory map from the on-disk index. Returns the
        loaded models; unreadable entries are skipped (a torn artifact
        must not block serving the rest)."""
        path = os.path.join(self.root, INDEX_FILE)
        if not os.path.exists(path):
            return []
        with open(path) as fd:
            index = json.load(fd)
        loaded = []
        for model_id, entry in sorted(
                (index.get("models") or {}).items()):
            try:
                with open(os.path.join(self.root, entry["file"]),
                          "rb") as fd:
                    rec = pickle.load(fd)
                forest = trees.Forest(
                    *[jnp.asarray(rec["forest"][f])
                      for f in trees.Forest._fields])
                model = RegisteredModel(
                    model_id=model_id,
                    config_keys=tuple(rec["config_keys"]),
                    config_index=rec["config_index"], forest=forest,
                    mu=jnp.asarray(rec["mu"]),
                    wmat=jnp.asarray(rec["wmat"]), cols=rec["cols"],
                    depth=rec["depth"], seed=rec["seed"],
                    max_depth=rec["max_depth"],
                )
            except (OSError, KeyError, ValueError,
                    pickle.UnpicklingError, EOFError):
                continue
            self._models[model_id] = model
            loaded.append(model)
        return loaded
