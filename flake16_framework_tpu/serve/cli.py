"""The ``serve`` CLI verb: stand the scoring service up, drive it with a
closed-loop client load, print one JSON stats line.

    python -m flake16_framework_tpu serve [--synth N] [--trees T]
        [--max-depth D] [--ledger scores.pkl] [--limit K]
        [--requests N] [--rows R] [--clients C]
        [--kinds predict,shap] [--buckets 8,32,128]
        [--registry DIR] [--json]
        [--hold] [--hold-timeout S] [--drain-deadline S]
        [--metrics-port P] [--slo] [--slo-p99-ms MS]
        [--fleet W] [--workdir DIR] [--rolling-restart]
        [--worker --socket PATH]

``--metrics-port P`` stands the Prometheus exporter up on loopback port
P (0 = ephemeral; the bound port prints as ``METRICS_PORT <p>``), and —
like ``--slo`` — arms the SLO monitor: declared objectives
(``--slo-p99-ms``) evaluated as multi-window burn rates that shed load
at admission and step the degradation ladder on breach (obs/slo.py).

Without ``--ledger`` it fits + registers the study's two SHAP configs
(config.SHAP_CONFIGS) on synthetic data; with it, every config the
sweep's scores ledger holds (canonical grid order, ``--limit`` bounds
the count). ``--registry DIR`` persists the artifacts (register ->
reload round-trips). ``sustained_load`` is the same closed-loop driver
bench.py --serve measures with — the CLI is the interactive arm of the
sustained-throughput benchmark.

``--hold`` is the drain drill's child half (ISSUE 11b): serve a
closed-loop load until SIGTERM (or ``--hold-timeout``), then
``ScoringService.drain`` and print one ``DRAIN_ACCT {json}`` line.
Exit 0 iff the drain completed within the deadline and every client
request was accounted for (completed, or retriably rejected) — zero
silent drops. ``tools/chaos_drill.py serve`` is the parent half.

ISSUE 18 adds the fleet modes. ``--fleet W`` fits + persists the
registry, spawns W worker processes (serve/fleet.Fleet) over it,
stands the health-gated hedging router up (serve/router.FleetRouter),
and drives the SAME ``sustained_load`` through the router — the fleet
is a drop-in ScoringService from the driver's side. With
``--rolling-restart`` the load is followed by a zero-drop rolling
restart walk. ``--worker --socket PATH --registry DIR`` is the child
half the fleet spawns: load the persisted registry (no fitting), warm,
answer wire-protocol frames until drained.

ISSUE 19 federates the observability plane over the fleet: with
``--fleet``, ``--metrics-port`` stands up ONE exporter for the whole
fleet (per-worker series labeled ``worker="<i>"``, fleet aggregates
from the router), ``--slo`` arms the fleet-level burn monitor (accounts
and deprioritizes hot workers, never sheds), and the ``--json`` result
carries ``fleet.slo`` + ``fleet.rps``.
"""

import json
import sys
import threading
import time


def sustained_load(service, feats, model_ids, *, n_requests=256, rows=16,
                   kinds=("predict",), clients=8, timeout=120.0):
    """Closed-loop client load: ``clients`` threads, each scoring its
    share of ``n_requests`` synchronously (round-robin over models and
    kinds, sliding row windows over ``feats``). Returns the measured
    stats dict: requests, wall_s, rps, p50/p99, errors."""
    n_clients = max(1, min(int(clients), int(n_requests)))
    per = int(n_requests) // n_clients
    errors = []
    lock = threading.Lock()

    def client(ci):
        for i in range(per):
            j = ci * per + i
            model_id = model_ids[j % len(model_ids)]
            kind = kinds[j % len(kinds)]
            off = (j * rows) % max(1, feats.shape[0] - rows)
            try:
                service.score(model_id, feats[off:off + rows], kind=kind,
                              timeout=timeout)
            except Exception as e:
                with lock:
                    errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    total = per * n_clients
    snap = service.latency.snapshot()
    svc = service.stats()
    return {
        "requests": total,
        "completed": snap["count"],
        "clients": n_clients,
        "rows": rows,
        "kinds": list(kinds),
        "wall_s": round(wall, 4),
        "rps": round(total / wall, 2) if wall > 0 else None,
        "p50_ms": snap["p50_ms"],
        "p99_ms": snap["p99_ms"],
        "queue_depth": svc["queue_depth"],
        "quarantined": sorted(svc["quarantined"]),
        "errors": errors[:8],
        "n_errors": len(errors),
    }


def hold_until_signal(service, feats, model_ids, *, rows=16,
                      kinds=("predict",), clients=8, hold_timeout=120.0,
                      drain_deadline=10.0):
    """The drain drill's child half: drive a closed-loop load, print
    ``SERVE_READY``, wait for SIGTERM/SIGINT (bounded by
    ``hold_timeout``), then drain. Every client request ends in exactly
    one bucket — ok (future completed), retriable (drain rejection:
    safe to resubmit), rejected (non-retriable admission), failed
    (anything else) — so "zero silently dropped" is checkable from the
    returned counts alone."""
    import signal

    from flake16_framework_tpu.serve.queue import RequestRejected

    stop_evt = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop_evt.set())
    signal.signal(signal.SIGINT, lambda *_: stop_evt.set())

    counts = {"ok": 0, "retriable": 0, "rejected": 0, "failed": 0}
    lock = threading.Lock()
    n_clients = max(1, int(clients))

    def client(ci):
        j = ci
        while True:
            model_id = model_ids[j % len(model_ids)]
            kind = kinds[j % len(kinds)]
            off = (j * rows) % max(1, feats.shape[0] - rows)
            try:
                service.score(model_id, feats[off:off + rows], kind=kind,
                              timeout=60.0)
                k = "ok"
            except Exception as e:
                k = ("retriable" if getattr(e, "retriable", False)
                     else "rejected" if isinstance(e, RequestRejected)
                     else "failed")
            with lock:
                counts[k] += 1
            if k != "ok":
                return

    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(n_clients)]
    for t in threads:
        t.start()
    print("SERVE_READY", flush=True)
    stop_evt.wait(hold_timeout)
    acct = service.drain(deadline_s=drain_deadline)
    for t in threads:
        t.join(10.0)
    return {"drain": acct, "counts": dict(counts),
            "signalled": stop_evt.is_set()}


def _parse(args):
    opts = {
        "synth": 512, "trees": 16, "max_depth": 12, "ledger": None,
        "limit": None, "requests": 256, "rows": 16, "clients": 8,
        # None = consult the performance observatory for a recorded
        # bucket ladder, falling through to service.DEFAULT_BUCKETS
        # (obs/perfdb.serve_buckets); --buckets pins it explicitly.
        "kinds": ("predict",), "buckets": None,
        "registry": None, "json": False,
        "hold": False, "hold_timeout": 120.0, "drain_deadline": 10.0,
        "metrics_port": None, "slo": False, "slo_p99_ms": 50.0,
        "worker": False, "socket": None,
        "fleet": None, "workdir": None, "rolling_restart": False,
    }
    it = iter(args)
    for a in it:
        if a == "--json":
            opts["json"] = True
        elif a == "--hold":
            opts["hold"] = True
        elif a == "--slo":
            opts["slo"] = True
        elif a == "--worker":
            opts["worker"] = True
        elif a == "--rolling-restart":
            opts["rolling_restart"] = True
        elif a in ("--hold-timeout", "--drain-deadline", "--slo-p99-ms"):
            opts[a[2:].replace("-", "_")] = float(next(it))
        elif a == "--metrics-port":
            opts["metrics_port"] = int(next(it))
        elif a in ("--synth", "--trees", "--max-depth", "--limit",
                   "--requests", "--rows", "--clients", "--fleet"):
            opts[a[2:].replace("-", "_")] = int(next(it))
        elif a == "--ledger":
            opts["ledger"] = next(it)
        elif a == "--registry":
            opts["registry"] = next(it)
        elif a == "--socket":
            opts["socket"] = next(it)
        elif a == "--workdir":
            opts["workdir"] = next(it)
        elif a == "--kinds":
            opts["kinds"] = tuple(next(it).split(","))
        elif a == "--buckets":
            opts["buckets"] = tuple(int(b) for b in next(it).split(","))
        else:
            raise ValueError(f"Unrecognized serve option {a!r}")
    return opts


def _fleet_main(opts, feats, registry):
    """The ``--fleet W`` body: spawn the worker fleet over the persisted
    registry, route the sustained load through the hedging router, then
    (optionally) walk a zero-drop rolling restart.

    The observability plane (ISSUE 19) hangs off the router here:
    ``--slo`` declares the FLEET objectives (the router's monitor
    accounts and deprioritizes, it never sheds — workers keep their own
    shedding monitors), and ``--metrics-port`` stands up the single
    FEDERATED exporter — per-worker series labeled ``worker="<i>"``
    plus fleet aggregates, one endpoint for the whole fleet (workers
    never open their own)."""
    import os
    import tempfile

    from flake16_framework_tpu.serve.fleet import Fleet
    from flake16_framework_tpu.serve.router import FleetRouter

    workdir = opts["workdir"] or tempfile.mkdtemp(prefix="f16-fleet-")
    os.makedirs(workdir, exist_ok=True)
    slo_p99 = opts["slo_p99_ms"] if opts["slo"] else None
    fleet_slo = None  # default: router still accounts with defaults
    if opts["slo"]:
        from flake16_framework_tpu.obs.slo import SLOConfig

        fleet_slo = SLOConfig(p99_ms=opts["slo_p99_ms"])
    with Fleet(registry.root, opts["fleet"], workdir=workdir,
               buckets=opts["buckets"], slo_p99_ms=slo_p99) as fleet:
        with FleetRouter(fleet, slo=fleet_slo) as router:
            metrics_srv = None
            if opts["metrics_port"] is not None:
                from flake16_framework_tpu.obs import metrics as _metrics

                reg = _metrics.MetricsRegistry()
                _metrics.register_process_sources(reg)
                _metrics.register_fleet_sources(reg, router)
                metrics_srv = _metrics.MetricsServer(
                    reg, port=opts["metrics_port"]).start()
                print(f"METRICS_PORT {metrics_srv.port}", flush=True)
            try:
                result = sustained_load(
                    router, feats, registry.ids(),
                    n_requests=opts["requests"], rows=opts["rows"],
                    kinds=opts["kinds"], clients=opts["clients"])
                if opts["rolling_restart"]:
                    result["rolling_restart"] = router.rolling_restart(
                        drain_deadline_s=opts["drain_deadline"])
                stats = router.stats()
                result["fleet"] = {
                    "workers": opts["fleet"],
                    "pids": fleet.pids(),
                    "router": stats["router"],
                    "rps": stats["rps"],
                    "slo": stats["slo"],
                    "failover_s": router.last_failover_s,
                    "per_worker": [w["hb"].get("requests")
                                   for w in stats["workers"]],
                }
                if metrics_srv is not None:
                    result["fleet"]["metrics_port"] = metrics_srv.port
            finally:
                if metrics_srv is not None:
                    metrics_srv.stop()
    result["models"] = registry.ids()
    print(json.dumps(result) if opts["json"]
          else json.dumps(result, indent=1))
    sys.stdout.flush()
    return 1 if result["n_errors"] else 0


def serve_main(args):
    opts = _parse(args)

    if opts["worker"]:
        from flake16_framework_tpu.serve.fleet import worker_main

        return worker_main(opts)

    from flake16_framework_tpu import config as cfg
    from flake16_framework_tpu.serve.registry import ModelRegistry
    from flake16_framework_tpu.serve.service import ScoringService
    from flake16_framework_tpu.utils import synth

    feats, labels, _ = synth.make_dataset(n_tests=opts["synth"], seed=7)

    if opts["fleet"] and not opts["registry"]:
        # Workers load artifacts from disk — a fleet NEEDS a persisted
        # registry; default one under the (possibly ephemeral) workdir.
        import os as _os
        import tempfile as _tempfile

        opts["workdir"] = (opts["workdir"]
                           or _tempfile.mkdtemp(prefix="f16-fleet-"))
        opts["registry"] = _os.path.join(opts["workdir"], "registry")

    persist = opts["registry"] is not None
    registry = ModelRegistry(opts["registry"] or "serve-registry")
    overrides = {"Extra Trees": opts["trees"],
                 "Random Forest": opts["trees"]}
    if opts["ledger"]:
        registry.register_from_ledger(
            opts["ledger"], feats, labels, limit=opts["limit"],
            max_depth=opts["max_depth"], tree_overrides=overrides,
            persist=persist)
    else:
        for keys in cfg.SHAP_CONFIGS:
            registry.fit_and_register(
                keys, feats, labels, max_depth=opts["max_depth"],
                tree_overrides=overrides, persist=persist)

    if opts["fleet"]:
        return _fleet_main(opts, feats, registry)

    slo_cfg = None
    if opts["slo"] or opts["metrics_port"] is not None:
        # The SLO loop rides along whenever the live plane is up: a
        # metrics endpoint without burn rates would expose gauges the
        # admission path ignores — the opposite of ROADMAP item 5.
        from flake16_framework_tpu.obs.slo import SLOConfig

        slo_cfg = SLOConfig(p99_ms=opts["slo_p99_ms"])

    with ScoringService(registry, buckets=opts["buckets"], slo=slo_cfg,
                        metrics_port=opts["metrics_port"]) as svc:
        if svc.metrics is not None:
            print(f"METRICS_PORT {svc.metrics.port}", flush=True)
        if opts["hold"]:
            result = hold_until_signal(
                svc, feats, registry.ids(), rows=opts["rows"],
                kinds=opts["kinds"], clients=opts["clients"],
                hold_timeout=opts["hold_timeout"],
                drain_deadline=opts["drain_deadline"])
        else:
            result = sustained_load(
                svc, feats, registry.ids(), n_requests=opts["requests"],
                rows=opts["rows"], kinds=opts["kinds"],
                clients=opts["clients"])
        slo_summary = svc.slo_summary()
        if slo_summary is not None:
            result["slo"] = slo_summary

    import jax

    result["backend"] = jax.default_backend()
    result["models"] = registry.ids()
    if opts["hold"]:
        print("DRAIN_ACCT " + json.dumps(result), flush=True)
        ok = (result["drain"]["phase"] == "complete"
              and result["counts"]["failed"] == 0
              and result["counts"]["rejected"] == 0)
        return 0 if ok else 1
    print(json.dumps(result) if opts["json"]
          else json.dumps(result, indent=1))
    sys.stdout.flush()
    return 1 if result["n_errors"] else 0
