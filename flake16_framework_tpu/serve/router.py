"""FleetRouter: the health-gated, hedging, failing-over front end of
the serving fleet (ISSUE 18 tentpole).

The router duck-types the :class:`ScoringService` client API —
``score`` / ``submit`` / ``latency`` / ``stats()`` — so every existing
driver (``sustained_load``, ``hold_until_signal``, bench's serve
harness) runs against a W-worker fleet unchanged. Under the API:

**Health gating.** Each worker link carries the worker's pushed
heartbeats (serve/fleet.py streams queue depth, inflight, p50/p99, SLO
burn rates, shedding). A worker is ROUTABLE only while its link is up,
its last heartbeat is fresher than ``F16_FLEET_STALL_S``, it is not
shedding (SLO burn breach — the router respects the worker's own
admission verdict instead of hammering a breached replica), and it is
not draining. Selection is least-loaded: min(pending + queue_depth)
over routable links.

**Hedging.** ``score`` waits ``F16_FLEET_HEDGE_MS`` on the request
future, then re-sends the SAME request id to a different worker —
scoring is idempotent, so racing two replicas against a straggler is
free except for the duplicate's compute. The first response completes
the future; the loser's response finds the id already done and is
COALESCED (counted, dropped — never double-billed to the client).
Hedge pacing rides the resilience backoff machinery
(resilience/guard.BackoffPolicy): hedge k waits one backoff step
longer than hedge k-1.

**Failover.** A dead link (EOF/ECONNRESET — SIGKILL closes the socket
promptly) orphans its pending requests; each orphan that is not
already done is re-dispatched to a surviving worker after a
BackoffPolicy delay, bounded by the policy's ``max_attempts``. A
worker's RETRIABLE error response (drain rejection, shed) re-dispatches
the same way — nothing was dispatched on the request's behalf, the
ServeError contract — which is exactly why rolling restarts are
zero-drop. Failover timing is recorded: ``failovers`` keeps
{worker, t_detect, t_recovered, n_orphans} per event and
``last_failover_s`` feeds bench's ``fleet_failover_s``.

**Rolling restart.** ``rolling_restart`` walks workers one at a time:
mark the link draining (routing stops), send the ``drain`` op (the
worker runs the ISSUE-11b graceful drain and exits 0), wait for the
fleet manager's free respawn, reconnect, wait for a fresh heartbeat,
move on. Admission at the router never closes; queued-but-unstarted
requests the drain rejects come back retriable and re-route. The drill
asserts 0 client-visible errors across the whole walk.

**Observability plane (ISSUE 19).** Every submit mints an
``obs.mint_trace`` context; sampled requests carry it across the wire
(serve/wire.py trace fields) so worker spans adopt the router's trace
id, and the router's own ``fleet.request`` span plus its hedge /
hedge-coalesced / redispatch ``fleet`` events land on the SAME trace —
one Perfetto render (``trace --fleet``) shows a hedged, failed-over
request as one correlated story across every process it touched. A
fleet-level SLO monitor (obs/slo.SLOMonitor, ``degrade=False`` — it
accounts, it never sheds) folds the router-observed latency/error
stream into burn rates; a worker whose heartbeat carries a hot local
burn is DEPRIORITIZED in selection (a load penalty, not a gate — it
still serves when it is the only one standing), and rolling restarts
are annotated with the error-budget spend their window cost.

Lock discipline (f16race C-pack): the router's locks form a flat
order — a link's ``_lock`` guards that link's pending map + heartbeat
state, the router's ``_lock`` guards counters/failover records, a
request's internal lock is a completion leaf. No path holds two of
them except link→request (completion under the link's pop) and
router→nothing; lockwatch sees a cycle-free order.
"""

import collections
import os
import random
import threading
import time

import queue as _stdqueue

from flake16_framework_tpu import obs
from flake16_framework_tpu.obs import slo as _slo
from flake16_framework_tpu.serve import wire
from flake16_framework_tpu.serve.queue import (
    RequestRejected, RetriableRejection, ServeError,
)
from flake16_framework_tpu.serve.service import LatencyStats

# Straggler timeout before a hedge duplicate is sent, milliseconds.
HEDGE_ENV = "F16_FLEET_HEDGE_MS"
DEFAULT_HEDGE_MS = 400.0

# Heartbeat staleness horizon, seconds: a worker silent this long is
# un-routable (stalled or dead) even while its socket stays open.
STALL_ENV = "F16_FLEET_STALL_S"
DEFAULT_STALL_S = 2.0

# SLO deprioritization (ISSUE 19): a worker heartbeating a fast-window
# burn over 1.0 (spending faster than budget) has each excess burn unit
# priced as this many queued requests in the least-loaded pick. High
# enough to steer load away from a hot replica before it breaches and
# sheds; bounded (see WorkerLink.load) so a burning worker is never
# priced out entirely — deprioritized, not gated.
BURN_PENALTY_LOAD = 8.0

# Sliding window for the fleet requests-per-second aggregate, seconds.
RPS_WINDOW_S = 10.0


def hedge_ms_from_env(environ=None):
    env = os.environ if environ is None else environ
    raw = env.get(HEDGE_ENV, "")
    try:
        return float(raw) if raw else DEFAULT_HEDGE_MS
    except ValueError:
        return DEFAULT_HEDGE_MS


def stall_s_from_env(environ=None):
    env = os.environ if environ is None else environ
    raw = env.get(STALL_ENV, "")
    try:
        val = float(raw) if raw else DEFAULT_STALL_S
    except ValueError:
        val = DEFAULT_STALL_S
    return max(0.1, val)


class NoRoutableWorker(RetriableRejection):
    """Every worker is down/stalled/draining/shedding — retriable: the
    request was never dispatched anywhere."""


class FleetRequest:
    """One routed request's future. ``_complete``/``_fail`` return False
    when the request already finished — the hedge-coalescing check."""

    __slots__ = ("rid", "model_id", "x", "kind", "trace", "t_submit",
                 "attempts", "failover", "_evt", "_out", "_exc", "_lock")

    def __init__(self, rid, model_id, x, kind, trace=None):
        self.rid = rid
        self.model_id = model_id
        self.x = x
        self.kind = kind
        self.trace = trace   # obs.mint_trace ctx (None = unsampled)
        self.t_submit = time.perf_counter()
        self.attempts = []   # worker indices this request was sent to
        self.failover = False  # orphaned by a link death (accounting)
        self._evt = threading.Event()
        self._out = None
        self._exc = None
        self._lock = threading.Lock()

    def done(self):
        return self._evt.is_set()

    def _complete(self, out):
        with self._lock:
            if self._evt.is_set():
                return False
            self._out = out
            self._evt.set()
            return True

    def _fail(self, exc):
        with self._lock:
            if self._evt.is_set():
                return False
            self._exc = exc
            self._evt.set()
            return True

    def wait(self, timeout=None):
        return self._evt.wait(timeout)

    def result(self, timeout=None):
        if not self._evt.wait(timeout):
            raise TimeoutError(
                f"fleet request {self.rid} not completed in {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._out


def _rebuild_error(resp):
    """A worker's error response as the exception the in-process service
    would have raised — retriable flag preserved across the wire."""
    name = resp.get("error_type", "ServeError")
    msg = resp.get("error", "worker error")
    if resp.get("retriable"):
        return RetriableRejection(msg)
    if name == "RequestRejected":
        return RequestRejected(msg)
    return ServeError(f"[{name}] {msg}")


class WorkerLink:
    """The router's end of one worker connection: socket + send lock,
    reader thread, pending map, last-pushed heartbeat."""

    def __init__(self, index, socket_path, router):
        self.index = index
        self.socket_path = socket_path
        self.router = router
        self._sock = None
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()   # pending + hb + up/draining
        self.pending = {}               # rid -> FleetRequest
        self.hb = {}
        self.last_hb = 0.0
        self.up = False
        self.draining = False
        self._reader = None

    # -- connection lifecycle --------------------------------------------

    def connect(self, timeout=1.0):
        sock = wire.connect_unix(self.socket_path, timeout=timeout)
        with self._lock:
            self._sock = sock
            self.up = True
            self.draining = False
            # A fresh link is routable until the first heartbeat proves
            # otherwise; stamping now keeps the stall gate from
            # rejecting a just-respawned worker.
            self.last_hb = time.monotonic()
            self.hb = {}
        self._reader = threading.Thread(
            target=self._read_loop, args=(sock,),
            name=f"fleet-link-{self.index}", daemon=True)
        self._reader.start()

    def close(self):
        with self._lock:
            sock, self._sock = self._sock, None
            self.up = False
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _mark_down(self, sock):
        """Link death: flip down, orphan the pending map, hand the
        orphans to the router's failover path."""
        with self._lock:
            if self._sock is not sock:
                return  # an older incarnation's reader; already handled
            self._sock = None
            self.up = False
            orphans = list(self.pending.values())
            self.pending.clear()
        try:
            sock.close()
        except OSError:
            pass
        if orphans:
            self.router._on_link_down(self, orphans)

    # -- I/O ---------------------------------------------------------------

    def send_request(self, req, msg):
        """Register ``req`` pending and ship the frame; raises OSError
        (after marking the link down) when the socket is dead."""
        with self._lock:
            if not self.up or self._sock is None:
                raise OSError(f"link {self.index} is down")
            self.pending[req.rid] = req
            sock = self._sock
        try:
            with self._send_lock:
                wire.send_msg(sock, msg)
        except OSError:
            self._mark_down(sock)
            raise

    def send_control(self, msg):
        with self._lock:
            sock = self._sock
        if sock is None:
            raise OSError(f"link {self.index} is down")
        with self._send_lock:
            wire.send_msg(sock, msg)

    def _read_loop(self, sock):
        while True:
            try:
                msg = wire.recv_msg(sock)
            except (wire.WireError, OSError):
                msg = None
            if msg is None:
                self._mark_down(sock)
                return
            if not isinstance(msg, dict):
                continue
            if "hb" in msg:
                with self._lock:
                    self.hb = msg["hb"]
                    self.last_hb = time.monotonic()
                continue
            rid = msg.get("id")
            with self._lock:
                req = self.pending.pop(rid, None)
            if req is None:
                # A control response (drain/ping ack) or a response for
                # a request another link already completed.
                self.router._on_unmatched(self.index, msg)
                continue
            self.router._on_response(self, req, msg)

    # -- health ----------------------------------------------------------

    def routable(self, stall_s, now=None):
        now = time.monotonic() if now is None else now
        with self._lock:
            return (self.up and not self.draining
                    and (now - self.last_hb) < stall_s
                    and not self.hb.get("shedding", False))

    def load(self):
        """The selection metric: router-side pending + worker-reported
        queue depth and inflight, plus the SLO deprioritization penalty
        (ISSUE 19) — excess fast-window burn the worker heartbeats is
        priced as queued work, capped at 4 burn units so a hot replica
        is steered around, never starved."""
        with self._lock:
            base = (len(self.pending) + self.hb.get("queue_depth", 0)
                    + self.hb.get("inflight", 0))
            burn = self.hb.get("burn_fast", 0.0) or 0.0
        if burn > 1.0:
            base += min(burn - 1.0, 4.0) * BURN_PENALTY_LOAD
        return base

    def snapshot(self):
        with self._lock:
            return {"index": self.index, "up": self.up,
                    "draining": self.draining,
                    "pending": len(self.pending),
                    "hb_age_s": round(time.monotonic() - self.last_hb, 3),
                    "hb": dict(self.hb)}


class FleetRouter:
    """See module docstring. ``fleet`` is a serve/fleet.Fleet (used for
    respawn-aware rolling restarts); ``socket_paths`` alone suffices
    for routing/hedging/failover against externally managed workers."""

    def __init__(self, fleet=None, *, socket_paths=None, hedge_ms=None,
                 stall_s=None, backoff=None, max_attempts=None,
                 environ=None, seed=0, slo=None):
        from flake16_framework_tpu.resilience import guard as _guard

        env = os.environ if environ is None else environ
        if fleet is None and socket_paths is None:
            raise ValueError("FleetRouter needs a fleet or socket_paths")
        self.fleet = fleet
        paths = (socket_paths if socket_paths is not None
                 else fleet.socket_paths())
        self.links = [WorkerLink(i, p, self) for i, p in enumerate(paths)]
        self.hedge_ms = (hedge_ms_from_env(env) if hedge_ms is None
                         else float(hedge_ms))
        self.stall_s = (stall_s_from_env(env) if stall_s is None
                        else float(stall_s))
        self.backoff = backoff or _guard.policy_from_env(env)
        self.max_attempts = (self.backoff.max_attempts + 1
                             if max_attempts is None else int(max_attempts))
        self.latency = LatencyStats()
        self._rng = random.Random(seed)
        self._lock = threading.Lock()   # counters + failover records
        self._rid = 0
        self.completed = 0
        self.hedges = 0
        self.hedge_coalesced = 0
        self.redispatches = 0
        self.failovers = []             # {worker, t_detect, t_recovered,
        self._open_failover = None      #  n_orphans}
        self._repair_q = _stdqueue.Queue()
        self._stop = threading.Event()
        self._threads = []
        # Fleet-level SLO accounting (ISSUE 19): the merged latency/
        # error stream every worker's responses flow through, folded by
        # one monitor that NEVER sheds or degrades (accounting + the
        # load()-side deprioritization signal; admission stays open —
        # per-worker monitors own shedding). ``slo=False`` disables;
        # an SLOConfig customizes the objectives.
        self.slo = None
        if slo is not False:
            cfg = slo if isinstance(slo, _slo.SLOConfig) \
                else _slo.SLOConfig(degrade=False)
            cfg.degrade = False  # the fleet monitor must never actuate
            self.slo = _slo.SLOMonitor(cfg)
        # (monotonic ts, completed) samples the maintenance loop appends
        # ~1/s — the fleet_rps aggregate's sliding window.
        self._rps_window = collections.deque()

    # -- lifecycle -------------------------------------------------------

    def start(self):
        for link in self.links:
            try:
                link.connect()
            except OSError:
                pass  # the maintenance loop keeps trying
        self._stop.clear()
        self._threads = [
            threading.Thread(target=self._maintenance_loop,
                             name="fleet-router-maint", daemon=True),
            threading.Thread(target=self._repair_loop,
                             name="fleet-router-repair", daemon=True),
        ]
        for t in self._threads:
            t.start()
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(5.0)
        self._threads = []
        for link in self.links:
            link.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- maintenance (reconnect + failover recovery bookkeeping) ---------

    def _maintenance_loop(self):
        next_obs = 0.0
        while not self._stop.wait(0.1):
            for link in self.links:
                with link._lock:
                    down = not link.up
                if down and not self._stop.is_set():
                    try:
                        link.connect(timeout=0.5)
                    except OSError:
                        continue
            now = time.monotonic()
            if now >= next_obs:
                next_obs = now + 1.0
                self._observe_fleet(now)

    def _observe_fleet(self, now=None):
        """The ~1 Hz fleet accounting tick: advance the rps window,
        evaluate the fleet SLO monitor (its breach/recovered ``slo``
        events are the fleet-level burn witness), and stamp the fleet
        aggregate gauges — all no-ops beyond an is-None check when
        telemetry is off."""
        now = time.monotonic() if now is None else now
        snaps = [link.snapshot() for link in self.links]
        with self._lock:
            self._rps_window.append((now, self.completed))
            while len(self._rps_window) > 2 \
                    and now - self._rps_window[0][0] > RPS_WINDOW_S:
                self._rps_window.popleft()
        if self.slo is not None:
            self.slo.evaluate()
        obs.gauge("fleet.rps", self.fleet_rps())
        obs.gauge("fleet.queue_depth",
                  sum(s["hb"].get("queue_depth", 0) for s in snaps))
        obs.gauge("fleet.inflight",
                  sum(s["hb"].get("inflight", 0) for s in snaps))
        obs.gauge("fleet.workers_up", sum(1 for s in snaps if s["up"]))

    def fleet_rps(self):
        """Completed requests per second over the sliding window the
        maintenance loop samples (0.0 until two samples exist)."""
        with self._lock:
            if len(self._rps_window) < 2:
                return 0.0
            t0, c0 = self._rps_window[0]
            t1, c1 = self._rps_window[-1]
        if t1 <= t0:
            return 0.0
        return round((c1 - c0) / (t1 - t0), 3)

    def _repair_loop(self):
        """Re-dispatch orphaned/rejected requests off the reader threads
        (the reader must never sleep a backoff)."""
        while not self._stop.is_set():
            try:
                req, attempt, exclude = self._repair_q.get(timeout=0.1)
            except _stdqueue.Empty:
                continue
            if req.done():
                self._note_recovered(req)
                continue
            # Floor the retry pacing at 50 ms even when the env pins
            # F16_FAULT_BACKOFF_S=0 (the drills do): instant retries
            # would burn every attempt inside one unroutable instant —
            # a respawn or shed-recovery needs a beat to land.
            delay = max(self.backoff.delay_s(attempt, self._rng), 0.05) \
                if attempt >= 1 else 0.0
            if delay:
                time.sleep(min(delay, 2.0))
            try:
                link = self._dispatch(req, exclude=exclude)
                with self._lock:
                    self.redispatches += 1
                if req.trace:
                    # Failover/retriable re-dispatch on the request's
                    # own trace: the merged render shows the hop.
                    obs.event("fleet", action="redispatch",
                              worker=link.index, rid=req.rid,
                              failover=req.failover,
                              trace_id=req.trace["trace_id"])
            except NoRoutableWorker:
                if attempt + 1 >= self.max_attempts:
                    req._fail(NoRoutableWorker(
                        f"no routable worker after {attempt + 1} "
                        f"attempts (request {req.rid})"))
                    self._note_recovered(req)
                else:
                    self._repair_q.put((req, attempt + 1, exclude))

    # -- dispatch --------------------------------------------------------

    def _routable_links(self, exclude=()):
        now = time.monotonic()
        return [l for l in self.links
                if l.index not in exclude and l.routable(self.stall_s, now)]

    def _pick(self, exclude=()):
        candidates = self._routable_links(exclude)
        if not candidates and exclude:
            # Better a hedge/retry on an already-tried worker than none.
            candidates = self._routable_links()
        if not candidates:
            raise NoRoutableWorker(
                "no routable fleet worker (all down, stalled, draining, "
                "or shedding)")
        return min(candidates, key=lambda l: l.load())

    def _dispatch(self, req, exclude=()):
        """Send ``req`` to the best routable worker; walks the candidate
        set on send failure. Raises NoRoutableWorker when nobody takes
        it (nothing was dispatched — retriable by contract)."""
        tried = set(exclude)
        msg = {"id": req.rid, "op": "score", "model": req.model_id,
               "kind": req.kind, "x": req.x}
        if req.trace:
            # Cross-process trace context (ISSUE 19) — sampled requests
            # only, so an unsampled frame stays byte-identical to the
            # pre-trace wire. The router's span id is the worker's
            # parent: its serve.request span nests under fleet.request.
            msg["trace_id"] = req.trace["trace_id"]
            msg["parent_id"] = req.trace["span_id"]
        while True:
            link = self._pick(tried)
            try:
                link.send_request(req, msg)
            except OSError:
                tried.add(link.index)
                if len(tried) >= len(self.links) * 2:
                    raise NoRoutableWorker(
                        "every fleet worker refused the dispatch")
                continue
            req.attempts.append(link.index)
            return link

    # -- reader callbacks ------------------------------------------------

    def _on_response(self, link, req, msg):
        ok = bool(msg.get("ok"))
        if ok:
            first = req._complete(msg.get("out"))
        else:
            exc = _rebuild_error(msg)
            if getattr(exc, "retriable", False) and not req.done():
                # The worker never dispatched (drain/shed rejection):
                # re-route — the zero-drop half of rolling restarts.
                self._repair_q.put((req, 0, (link.index,)))
                return
            first = req._fail(exc)
        if first:
            latency_ms = (time.perf_counter() - req.t_submit) * 1000.0
            self.latency.record(latency_ms)
            if self.slo is not None:
                # The merged fleet stream: every first completion from
                # ANY worker, errors included — the burn the rolling
                # restart annotation and `serve --json` report.
                self.slo.observe(latency_ms=latency_ms if ok else None,
                                 error=not ok)
            with self._lock:
                self.completed += 1
            if req.trace:
                # The router's half of the cross-process trace: one
                # fleet.request span per sampled request, on the same
                # trace id the worker's serve.request span adopted.
                obs.event("span", name="fleet.request",
                          wall_s=round(latency_ms / 1000.0, 6),
                          cold=False, trace_id=req.trace["trace_id"],
                          span_id=req.trace["span_id"],
                          model_id=req.model_id, req_kind=req.kind,
                          worker=link.index, ok=ok,
                          attempts=len(req.attempts),
                          failover=req.failover)
            self._note_recovered(req)
        else:
            with self._lock:
                self.hedge_coalesced += 1
            if req.trace:
                # The hedge LOSER, on the same trace as the winner.
                obs.event("fleet", action="hedge-coalesced",
                          worker=link.index, rid=req.rid,
                          trace_id=req.trace["trace_id"])

    def _on_unmatched(self, index, msg):
        """A response whose rid has no pending entry on that link: a
        hedged duplicate another link already answered, or a control
        ack handled synchronously elsewhere."""
        if msg.get("op_ack") or "acct" in msg or "stats" in msg \
                or "worker" in msg:
            return
        with self._lock:
            self.hedge_coalesced += 1

    def _on_link_down(self, link, orphans):
        live = [r for r in orphans if not r.done()]
        with self._lock:
            if live:
                for req in live:
                    req.failover = True
                if self._open_failover is None:
                    self._open_failover = {
                        "worker": link.index,
                        "t_detect": time.monotonic(),
                        "t_recovered": None,
                        "n_orphans": 0,
                        "outstanding": 0,
                    }
                self._open_failover["n_orphans"] += len(live)
                self._open_failover["outstanding"] += len(live)
        obs.event("fleet", action="link-down", worker=link.index,
                  orphans=len(live),
                  trace_ids=[r.trace["trace_id"]
                             for r in live if r.trace])
        for req in live:
            # attempt=1 → one backoff step before the re-dispatch; the
            # dead worker is excluded outright.
            self._repair_q.put((req, 1, (link.index,)))

    def _note_recovered(self, req):
        """Failover bookkeeping: when the last outstanding ORPHAN (not
        just any request) settles, the failover window closes."""
        if not req.failover:
            return
        with self._lock:
            if not req.failover:
                return
            req.failover = False
            fo = self._open_failover
            if fo is None:
                return
            fo["outstanding"] -= 1
            if fo["outstanding"] <= 0:
                fo["t_recovered"] = time.monotonic()
                fo.pop("outstanding")
                self.failovers.append(fo)
                self._open_failover = None

    @property
    def last_failover_s(self):
        with self._lock:
            if not self.failovers:
                return None
            fo = self.failovers[-1]
            return round(fo["t_recovered"] - fo["t_detect"], 4)

    # -- client API (ScoringService duck type) ---------------------------

    def submit(self, model_id, x, kind="predict"):
        with self._lock:
            self._rid += 1
            rid = self._rid
        # The fleet's ONE sampling decision (F16_TRACE_SAMPLE) — minted
        # here, carried on the wire, adopted by every worker the request
        # touches. None (telemetry off / coin lost) costs nothing
        # downstream: no wire fields, no events.
        req = FleetRequest(rid, model_id, x, kind, trace=obs.mint_trace())
        try:
            self._dispatch(req)
        except NoRoutableWorker:
            # Give the repair loop (and the fleet's respawn) a chance
            # before surfacing the rejection.
            self._repair_q.put((req, 1, ()))
        return req

    def score(self, model_id, x, kind="predict", timeout=None):
        """Synchronous submit + hedged wait: after ``hedge_ms`` of
        silence the request is duplicated to another worker (same rid —
        the late response coalesces)."""
        req = self.submit(model_id, x, kind=kind)
        deadline = (time.perf_counter() + timeout) if timeout else None
        hedge_s = self.hedge_ms / 1000.0
        hedge_n = 0
        while True:
            # Hedge pacing through the resilience backoff schedule:
            # hedge k waits the straggler horizon plus the k-th backoff
            # step, so a fleet-wide slowdown doesn't fan out a hedge
            # storm at a fixed cadence.
            wait_s = hedge_s + (self.backoff.delay_s(hedge_n, self._rng)
                                if hedge_n else 0.0)
            if deadline is not None:
                wait_s = min(wait_s, deadline - time.perf_counter())
                if wait_s <= 0:
                    return req.result(0.0)
            if req.wait(wait_s):
                return req.result(0.0)
            if hedge_n + 1 < self.max_attempts:
                hedge_n += 1
                try:
                    link = self._dispatch(req, exclude=tuple(req.attempts))
                    with self._lock:
                        self.hedges += 1
                    if req.trace:
                        # The hedge duplicate, on the request's trace.
                        obs.event("fleet", action="hedge",
                                  worker=link.index, rid=req.rid,
                                  hedge_n=hedge_n,
                                  trace_id=req.trace["trace_id"])
                except NoRoutableWorker:
                    pass  # keep waiting on the original

    def stats(self):
        snap = self.latency.snapshot()
        workers = [l.snapshot() for l in self.links]
        quarantined = sorted({q for w in workers
                              for q in w["hb"].get("quarantined", [])})
        with self._lock:
            counters = {"completed": self.completed,
                        "hedges": self.hedges,
                        "hedge_coalesced": self.hedge_coalesced,
                        "redispatches": self.redispatches,
                        "failovers": len(self.failovers)}
        return {
            "models": sorted({m for w in workers
                              for m in (w["hb"].get("models") or [])}),
            "requests": snap["count"],
            "p50_ms": snap["p50_ms"],
            "p99_ms": snap["p99_ms"],
            "queue_depth": sum(w["hb"].get("queue_depth", 0)
                               for w in workers),
            "quarantined": quarantined,
            "workers": workers,
            "router": counters,
            "rps": self.fleet_rps(),
            "slo": self.slo.summary() if self.slo is not None else None,
        }

    def scrape_worker_stats(self, indices=None, timeout_s=2.0):
        """On-demand worker scrape (ISSUE 19): a synchronous ``stats``
        round-trip per worker over a SIDE connection, so the routing
        link's pending map and latency accounting never see control
        traffic. Returns {worker index: stats dict}; a worker that is
        down or silent within ``timeout_s`` is simply absent — the
        federated exporter treats that like any other absent source."""
        out = {}
        links = (self.links if indices is None
                 else [self.links[i] for i in indices])
        for link in links:
            try:
                sock = wire.connect_unix(link.socket_path,
                                         timeout=timeout_s)
            except OSError:
                continue
            try:
                sock.settimeout(timeout_s)
                wire.send_msg(sock, {"id": 0, "op": "stats"})
                deadline = time.monotonic() + timeout_s
                while time.monotonic() < deadline:
                    msg = wire.recv_msg(sock)
                    if msg is None:
                        break
                    # Heartbeat pushes arrive on this connection too —
                    # skip them; only the stats response ends the read.
                    if isinstance(msg, dict) and "stats" in msg:
                        out[link.index] = msg["stats"]
                        break
            except (wire.WireError, OSError):
                pass
            finally:
                try:
                    sock.close()
                except OSError:
                    pass
        return out

    # -- rolling restart -------------------------------------------------

    def rolling_restart(self, *, drain_deadline_s=15.0,
                        ready_timeout_s=300.0):
        """Zero-drop rolling restart: walk workers one at a time through
        drain → exit 0 → fleet respawn → reconnect → fresh heartbeat.
        Requires a managed fleet. Returns per-worker step records; the
        chaos drill asserts 0 errors rode along client-side."""
        if self.fleet is None:
            raise ValueError("rolling_restart needs a managed fleet")
        walk_t0 = time.monotonic()
        walk_before = (self.slo.budget_snapshot()
                       if self.slo is not None else None)
        steps = []
        for link in self.links:
            t0 = time.monotonic()
            step_before = (self.slo.budget_snapshot()
                           if self.slo is not None else None)
            handle = self.fleet.workers[link.index]
            old_pid = handle.pid
            with link._lock:
                link.draining = True
            obs.event("fleet", action="rolling-drain", worker=link.index,
                      pid=old_pid)
            # The drain op must actually land: the link may be down
            # (e.g. this worker restarted earlier and the maintenance
            # loop hasn't reconnected yet) — reconnect and retry, and
            # re-pin draining after every connect() (connect resets it).
            deadline = time.monotonic() + ready_timeout_s
            sent = False
            while not sent and handle.alive() \
                    and time.monotonic() < deadline:
                try:
                    link.send_control({"id": 0, "op": "drain",
                                       "deadline_s": drain_deadline_s})
                    sent = True
                except OSError:
                    try:
                        link.close()
                        link.connect()
                        with link._lock:
                            link.draining = True
                    except OSError:
                        time.sleep(0.1)
            # The worker drains, acks (consumed as an unmatched control
            # response), exits 0; the fleet monitor respawns it.
            while handle.pid == old_pid or not handle.alive():
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"worker {link.index} not respawned within "
                        f"{ready_timeout_s}s")
                time.sleep(0.1)
            self.fleet.wait_ready(
                [link.index],
                timeout_s=max(1.0, deadline - time.monotonic()))
            # Reconnect eagerly (the maintenance loop would too) and
            # wait for a fresh heartbeat before moving to the next
            # worker — "one at a time" means never two un-warm workers.
            link.close()
            try:
                link.connect()
            except OSError:
                pass
            while True:
                with link._lock:
                    if link.up and link.hb:
                        break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"worker {link.index} respawned but no "
                        f"heartbeat within {ready_timeout_s}s")
                time.sleep(0.05)
            step = {"worker": link.index, "old_pid": old_pid,
                    "new_pid": handle.pid,
                    "wall_s": round(time.monotonic() - t0, 3)}
            if step_before is not None:
                # What this worker's drain window cost the fleet error
                # budget (ISSUE 19) — the restart's operability price.
                step["budget"] = _slo.budget_spend(
                    step_before, self.slo.budget_snapshot(),
                    self.slo.config)
            steps.append(step)
            obs.event("fleet", action="rolling-done", worker=link.index,
                      new_pid=handle.pid,
                      wall_s=step["wall_s"],
                      budget_burn=step.get("budget", {}).get("burn"))
        result = {"workers": len(steps), "steps": steps,
                  "wall_s": round(time.monotonic() - walk_t0, 3)}
        if walk_before is not None:
            result["budget"] = _slo.budget_spend(
                walk_before, self.slo.budget_snapshot(), self.slo.config)
        return result
