"""Preprocessing as one affine transform — StandardScaler and Scaler->PCA.

The reference's preprocessing axis (/root/reference/experiment.py:82-86) is
{None, StandardScaler, Pipeline(StandardScaler -> PCA(random_state=0))}, fit on
the FULL dataset before CV (experiment.py:452-453 — the leakage is faithful
behavior, SURVEY.md §2 row 15).

TPU-first observation: all three are affine maps ``x' = (x - mu) @ W``, so the
axis is *runtime data* — a ``lax.switch`` over three parameter builders inside
one jitted graph — not three compiled variants. PCA(n_components=None,
whiten=False) keeps all components; SVD on the [N, F<=16] matrix is tiny for XLA.

Sign convention follows the reference pin (sklearn 1.0.2 ``PCA._fit_full``:
``svd_flip`` with u_based_decision=True — per-component sign from the largest-
magnitude entry of U). Sign choice is irrelevant to downstream tree F1 (splits
mirror), but we keep the pinned convention for artifact comparability.
"""

import jax.numpy as jnp
from jax import lax

from flake16_framework_tpu.config import PREP_NONE, PREP_SCALING, PREP_PCA  # noqa: F401 (codes documented here)


def _scaler_params(x):
    """StandardScaler(with_mean=True, with_std=True), ddof=0; zero-variance
    columns get scale 1 (sklearn _handle_zeros_in_scale)."""
    mu = x.mean(axis=0)
    sd = jnp.sqrt(jnp.maximum(x.var(axis=0), 0.0))
    sd = jnp.where(sd == 0.0, 1.0, sd)
    return mu, sd


def fit_preprocess(x, prep_code):
    """Return (mu [F], W [F,F]) such that transform(x) == (x - mu) @ W for the
    preprocessing selected by ``prep_code`` (PREP_NONE/PREP_SCALING/PREP_PCA).
    Jit-safe: ``prep_code`` is a traced int32 dispatched with lax.switch.
    """
    n, f = x.shape
    dt = x.dtype

    def none_():
        return jnp.zeros((f,), dt), jnp.eye(f, dtype=dt)

    def scaling_():
        mu, sd = _scaler_params(x)
        return mu, jnp.diag(1.0 / sd).astype(dt)

    def pca_():
        mu, sd = _scaler_params(x)
        xs = (x - mu) / sd
        mu2 = xs.mean(axis=0)  # ~0, kept for exactness (PCA re-centers)
        xc = xs - mu2
        _, _, vt = jnp.linalg.svd(xc, full_matrices=False)
        # svd_flip(u_based): sign from U's max-|.| row; U column = Xc @ v / s,
        # so sign(U[i,j]) == sign((Xc @ vt[j])[i]) and we avoid materializing U.
        proj = xc @ vt.T  # [N, F] = U * S
        idx = jnp.argmax(jnp.abs(proj), axis=0)
        signs = jnp.sign(proj[idx, jnp.arange(f)])
        signs = jnp.where(signs == 0, 1.0, signs)
        vt = vt * signs[:, None]
        w = jnp.diag(1.0 / sd).astype(dt) @ vt.T
        return mu + mu2 * sd, w

    return lax.switch(prep_code, (none_, scaling_, pca_))


def transform(x, mu, w):
    return (x - mu[None, :]) @ w
