"""Preprocessing as one affine transform — StandardScaler and Scaler->PCA.

The reference's preprocessing axis (/root/reference/experiment.py:82-86) is
{None, StandardScaler, Pipeline(StandardScaler -> PCA(random_state=0))}, fit on
the FULL dataset before CV (experiment.py:452-453 — the leakage is faithful
behavior, SURVEY.md §2 row 15).

TPU-first observation: all three are affine maps ``x' = (x - mu) @ W``, so the
axis is *runtime data* — a ``lax.switch`` over three parameter builders inside
one jitted graph — not three compiled variants. PCA(n_components=None,
whiten=False) keeps all components; SVD on the [N, F<=16] matrix is tiny for XLA.

Sign convention follows the reference pin (sklearn 1.0.2 ``PCA._fit_full``:
``svd_flip`` with u_based_decision=True — per-component sign from the largest-
magnitude entry of U). Sign choice is irrelevant to downstream tree F1 (splits
mirror), but we keep the pinned convention for artifact comparability.

Backend split (trace-time): the component basis comes from
``jnp.linalg.svd(xc)`` on CPU (LAPACK, microseconds at [N,16]) but from
``jnp.linalg.eigh`` of the F×F Gram matrix on TPU. XLA:TPU lowers SVD of a
tall [N,F] matrix to a long iterative program whose single dispatch can
exceed the tunnel's device-fault envelope (~170 s — PROFILE.md; the round-3
``et_full`` probe step, the only PCA config probed, was the one step that
wedged the device). The Gram eigh is an [F,F]=16×16 problem — trivially
inside the envelope — and spans the same row space with identical ordering
(descending eigenvalue = descending singular value squared); the u-based
sign rule below resolves both factorizations' sign ambiguity the same way.
``F16_PCA_IMPL`` (svd|eigh) overrides for the on-device A/B.
"""

import os

import jax
import jax.numpy as jnp
from jax import lax

from flake16_framework_tpu.config import PREP_NONE, PREP_SCALING, PREP_PCA  # noqa: F401 (codes documented here)


def _scaler_params(x):
    """StandardScaler(with_mean=True, with_std=True), ddof=0; zero-variance
    columns get scale 1 (sklearn _handle_zeros_in_scale)."""
    mu = x.mean(axis=0)
    sd = jnp.sqrt(jnp.maximum(x.var(axis=0), 0.0))
    sd = jnp.where(sd == 0.0, 1.0, sd)
    return mu, sd


def _pca_basis(xc, pca_impl):
    """Component basis vt [F,F] (rows = components, descending variance) of the
    centered matrix ``xc``. Sign of each row is arbitrary here — the caller's
    u-based rule fixes it — so svd and eigh are interchangeable bases.

    The env/backend default resolves at TRACE time: a jitted caller caches the
    executable, so flipping ``F16_PCA_IMPL`` mid-process does NOT re-trace.
    In-process A/Bs must pass ``pca_impl`` explicitly per jit object (the
    hw_probe steps run one subprocess per arm for exactly this reason)."""
    impl = pca_impl or os.environ.get("F16_PCA_IMPL", "") or (
        "svd" if jax.default_backend() == "cpu" else "eigh")
    if impl not in ("svd", "eigh"):  # a typo'd A/B arm must not silently
        raise ValueError(f"pca_impl/F16_PCA_IMPL must be svd|eigh, got {impl!r}")
    if impl == "svd":
        _, _, vt = jnp.linalg.svd(xc, full_matrices=False)
        return vt
    # HIGHEST precision: the default TPU matmul runs bf16 passes, and the Gram
    # product is the only place the eigh arm can drift from the f32 LAPACK
    # convention the parity tests pin — the [F,N]@[N,F] product is tiny.
    gram = jnp.matmul(xc.T, xc, precision=lax.Precision.HIGHEST)
    _, evecs = jnp.linalg.eigh(gram)
    return evecs[:, ::-1].T


def fit_preprocess(x, prep_code, pca_impl=None):
    """Return (mu [F], W [F,F]) such that transform(x) == (x - mu) @ W for the
    preprocessing selected by ``prep_code`` (PREP_NONE/PREP_SCALING/PREP_PCA).
    Jit-safe: ``prep_code`` is a traced int32 dispatched with lax.switch.
    ``pca_impl`` (svd|eigh) pins the PCA factorization at trace time; default
    is by backend (see module docstring), ``F16_PCA_IMPL`` overrides.
    """
    n, f = x.shape
    dt = x.dtype

    def none_():
        return jnp.zeros((f,), dt), jnp.eye(f, dtype=dt)

    def scaling_():
        mu, sd = _scaler_params(x)
        return mu, jnp.diag(1.0 / sd).astype(dt)

    def pca_():
        mu, sd = _scaler_params(x)
        xs = (x - mu) / sd
        mu2 = xs.mean(axis=0)  # ~0, kept for exactness (PCA re-centers)
        xc = xs - mu2
        vt = _pca_basis(xc, pca_impl)
        # svd_flip(u_based): sign from U's max-|.| row; U column = Xc @ v / s,
        # so sign(U[i,j]) == sign((Xc @ vt[j])[i]) and we avoid materializing U.
        # [N, F] = U * S; HIGHEST so the TPU argmax/sign decision below reads
        # the same projections the CPU arm computes (bf16 passes can flip the
        # winner between two near-equal |proj| entries).
        proj = jnp.matmul(xc, vt.T, precision=lax.Precision.HIGHEST)
        idx = jnp.argmax(jnp.abs(proj), axis=0)
        signs = jnp.sign(proj[idx, jnp.arange(f)])
        signs = jnp.where(signs == 0, 1.0, signs)
        vt = vt * signs[:, None]
        w = jnp.diag(1.0 / sd).astype(dt) @ vt.T
        return mu + mu2 * sd, w

    return lax.switch(prep_code, (none_, scaling_, pca_))


def transform(x, mu, w):
    return (x - mu[None, :]) @ w
