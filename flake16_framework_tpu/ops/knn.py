"""Pairwise-distance / k-nearest-neighbour primitive.

One kernel feeds every resampler (SURVEY.md §7 step 5): squared Euclidean
distances via the matmul identity |a-b|^2 = |a|^2 + |b|^2 - 2ab — the 2ab term
is an [N,F]x[F,N] matmul that XLA tiles onto the MXU, which is exactly where
this work belongs on TPU (the reference does it in sklearn's Cython brute-force
kNN, /root/reference SURVEY §2 table B).

Masking convention: invalid columns (rows that are not candidate neighbours)
get +inf distance; the diagonal (self) is always +inf, matching sklearn's
NearestNeighbors(n_neighbors=k+1)[:, 1:] self-exclusion.
"""

import jax.numpy as jnp
from jax import lax


def pairwise_sq_dists(a, b):
    """[Na, F], [Nb, F] -> [Na, Nb] squared Euclidean distances (MXU matmul)."""
    aa = jnp.sum(a * a, axis=1)
    bb = jnp.sum(b * b, axis=1)
    d = aa[:, None] + bb[None, :] - 2.0 * (a @ b.T)
    return jnp.maximum(d, 0.0)


def masked_knn(x, col_valid, k):
    """k nearest valid neighbours of every row (self excluded).

    Returns (idx [N, k] int32, ok [N, k] bool) — ok marks neighbours that are
    real (valid column, not +inf padding). Ties resolve to the lowest index
    (lax.top_k is stable), matching brute-force sklearn ordering.
    """
    n = x.shape[0]
    d = pairwise_sq_dists(x, x)
    d = jnp.where(col_valid[None, :], d, jnp.inf)
    d = d.at[jnp.arange(n), jnp.arange(n)].set(jnp.inf)
    neg, idx = lax.top_k(-d, k)
    return idx.astype(jnp.int32), jnp.isfinite(neg)


def nearest_one(x, col_valid):
    """Index of the single nearest valid neighbour per row (ties -> lowest)."""
    n = x.shape[0]
    d = pairwise_sq_dists(x, x)
    d = jnp.where(col_valid[None, :], d, jnp.inf)
    d = d.at[jnp.arange(n), jnp.arange(n)].set(jnp.inf)
    return jnp.argmin(d, axis=1).astype(jnp.int32)
