"""Confusion accumulation and precision/recall/F1.

Replicates the reference scoring semantics (/root/reference/experiment.py:430-443,
476-486): the index trick ``k = 2*label + pred - 1`` maps (TN, FP, FN, TP) to
(-1, 0, 1, 2); TN is skipped; counts accumulate per project and in total; P/R/F
propagate ``None`` on zero denominators.

Device side is a single ``segment_sum`` over ``project_id * 3 + k`` — no Python
loops over samples — so it fuses into the jitted per-config scoring graph.
Host side formats counts into the reference's ``scores.pkl`` schema
(README.rst:78-134).
"""

import jax.numpy as jnp
import jax.ops


def confusion_by_project(labels, preds, test_mask, project_ids, n_projects):
    """Accumulate (FP, FN, TP) per project over fold-test samples.

    labels: [N] bool/int — true binary labels.
    preds: [..., N] predictions (leading axes e.g. folds).
    test_mask: [..., N] 0/1 — which samples are scored in each fold
      (reference scores only fold-test rows, experiment.py:460-482).
    project_ids: [N] int32.
    Returns counts [n_projects, 3] int32, ordered (FP, FN, TP).
    """
    labels = labels.astype(jnp.int32)
    preds = preds.astype(jnp.int32)
    k = 2 * labels[None, :] + preds.reshape(-1, labels.shape[0]) - 1
    mask = (test_mask.reshape(k.shape) > 0) & (k >= 0)

    seg = project_ids[None, :] * 3 + jnp.maximum(k, 0)
    counts = jax.ops.segment_sum(
        mask.astype(jnp.int32).ravel(), seg.ravel(), num_segments=n_projects * 3
    )
    return counts.reshape(n_projects, 3)


def div_none(a, b):
    return a / b if b else None


def get_prf(fp, fn, tp):
    """Precision/recall/F1 with None on zero denominators
    (reference experiment.py:430-443)."""
    p = div_none(tp, tp + fp)
    r = div_none(tp, tp + fn)

    if p is None or r is None:
        f = None
    else:
        f = div_none(2 * p * r, p + r)

    return p, r, f


def format_scores(counts, project_names, all_projects):
    """counts [P,3] -> (scores dict, scores_total list) in reference schema:
    ``scores[proj] = [fp, fn, tp, p, r, f]`` (README.rst:120-134).

    ``all_projects`` is the per-sample project string array: the reference seeds
    its dict from it (experiment.py:456), so projects keep dataset order and
    projects with zero scored samples still appear.
    """
    counts = [[int(x) for x in row] for row in counts]
    order = list(dict.fromkeys(project_names))

    scores = {}
    total = [0, 0, 0]
    for pid, proj in enumerate(order):
        fp, fn, tp = counts[pid]
        scores[proj] = [fp, fn, tp, *get_prf(fp, fn, tp)]
        total[0] += fp
        total[1] += fn
        total[2] += tp

    # Preserve reference dict ordering: first-seen order over the sample array.
    seen = {p: scores[p] for p in dict.fromkeys(list(all_projects))}
    scores_total = [*total, *get_prf(*total)]
    return seen, scores_total
