"""Tree-ensemble fit/predict as pure jitted JAX — the replacement for sklearn's
Cython tree stack (SURVEY.md §2 table B rows 1-3; reference call sites
/root/reference/experiment.py:96-98,469,473).

Design (TPU-first, not a port):

- **Static shapes.** A tree is a fixed-capacity structure-of-arrays
  (``Forest``): ``max_nodes`` slots regardless of data. Growth is breadth-first
  level-by-level under a ``lax.while_loop`` that stops as soon as no node can
  split (or the depth bound is hit); BFS allocation makes every level's new
  nodes a *contiguous* id range, so all node writes are
  ``dynamic_update_slice`` windows.
- **Scatter-free level step.** TPU lowers multi-thousand-segment
  ``segment_sum``/``segment_max`` to scatters, which serialize and dominated
  an earlier implementation; gathers and ``searchsorted`` serialize too
  (profiled at ~14 ms per [60x16x1000] gather on v5e), so the level step
  keeps them off the per-feature axis: one stable *multi-operand*
  ``lax.sort`` per feature puts (node-id, value, weights) in (node, value)
  order in a single op; run boundaries come from neighbor compares; within-
  run prefix sums and run totals are ``cummax``/``cummin`` propagations of
  the monotone cumsum (no positional gathers); the best candidate per node
  is a segmented suffix-scan; and run start/end positions are computed once
  per level from the raw rel ids (a bincount + cumsum — identical for every
  feature, since each feature's sorted array holds the same id multiset).
- **Integer-exact scoring.** Weighted counts are small integers, exact in f32;
  the gini proxy is reformulated as ``d_L^2/w_L + d_R^2/w_R`` with
  ``d = w0 - w1`` (equal to sklearn's proxy up to a per-node constant), which
  removes the large constant term and keeps comparisons well-conditioned
  without f64.
- **Masking, not dynamic shapes.** Fold membership, resampler validity, and
  bootstrap multiplicities all arrive as one per-sample weight vector; rows
  with zero weight (and rows whose node has finished) are parked in a dummy
  frontier slot and never influence splits, thresholds, or leaf values — the
  moral equivalent of sklearn fitting on a shorter array, under XLA's
  static-shape rules.

Replicated sklearn 1.0.2 semantics (defaults of the reference estimators):
gini, ``splitter=best``/``random``, unbounded depth (bounded here by a generous
``max_depth``), ``min_samples_split=2``, ``min_samples_leaf=1``,
``max_features=sqrt`` for the ensembles and all features for the single tree,
midpoint thresholds with the ``<=`` left rule, candidate features drawn in
random order skipping constant features, pure nodes never split.
"""

import functools
import os
import sys
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from flake16_framework_tpu.obs import costs as _costs
from flake16_framework_tpu.resilience import ladder as _res_ladder

# sklearn's FEATURE_THRESHOLD: two values closer than this are "equal" for
# split-candidate purposes.
FEATURE_EPS = 1e-7


class Forest(NamedTuple):
    """Structure-of-arrays tree ensemble. Shapes: [T, M] (+ [T, M, 2] value).

    ``feature`` is -1 at leaves; ``value`` holds *weighted class counts* for
    every node ever populated (internal nodes too — Tree SHAP needs node cover
    weights), normalized only at predict time like sklearn's predict_proba.
    """

    feature: jax.Array
    threshold: jax.Array
    left: jax.Array
    right: jax.Array
    value: jax.Array
    n_nodes: jax.Array
    max_depth: jax.Array  # scalar i32: depth bound used at fit time; predict
    # derives its traversal length from this so fit/predict can't disagree.


# Every Forest field with a tree axis (max_depth is broadcast metadata) —
# the single source of truth for slicing/concatenating forests by tree.
TREE_FIELDS = Forest._fields[:-1]


def slice_trees(forest, lo, hi, axis=0):
    """Forest restricted to trees [lo:hi] along ``axis`` (0 for a plain
    [T, ...] forest, 1 for a fold-stacked [folds, T, ...] one)."""
    idx = (slice(None),) * axis + (slice(lo, hi),)
    return forest._replace(
        **{f: getattr(forest, f)[idx] for f in TREE_FIELDS}
    )


def trim_nodes(forest, m):
    """Forest with the node axis cut to ``m`` slots (the last axis of
    feature/threshold/left/right, second-to-last of value). Safe whenever
    ``m >= max(n_nodes)``: slots past the used count are never referenced
    (child ids are < n_nodes). Shrinks the leaf-slot padding that
    per-(leaf, sample) workloads like Tree SHAP pay for."""
    idx = {f: (Ellipsis, slice(0, m)) for f in
           ("feature", "threshold", "left", "right")}
    idx["value"] = (Ellipsis, slice(0, m), slice(None))
    return forest._replace(
        **{f: getattr(forest, f)[i] for f, i in idx.items()}
    )


def concat_trees(parts, axis=0):
    """Concatenate Forests along the tree axis — the inverse of growing an
    ensemble in key-table slices (fit_forest* ``tree_keys``)."""
    return Forest(
        *[jnp.concatenate([getattr(p, f) for p in parts], axis=axis)
          for f in TREE_FIELDS],
        parts[0].max_depth,
    )


def _exclusive_cumsum(x):
    return jnp.concatenate([jnp.zeros_like(x[:1]), jnp.cumsum(x)[:-1]])


def _proxy_score(lw, lwy, rw, rwy, valid):
    """Weighted-gini proxy, maximized over candidates. Equal to sklearn's
    proxy up to a per-node constant: with d = w0 - w1 per side,
    d_L^2/w_L + d_R^2/w_R (see module docstring on conditioning)."""
    d_l = lw - 2.0 * lwy
    d_r = rw - 2.0 * rwy
    score = (
        d_l * d_l / jnp.maximum(lw, 1.0) + d_r * d_r / jnp.maximum(rw, 1.0)
    )
    return jnp.where(valid, score, -jnp.inf)


# Per-node feature-quota semantics (both growers; model-changing A/B knob
# like F16_ET_DRAW, read at import):
# - "informative" (default; round-2/3 behavior): select max_features
#   NON-constant features — constants never consume the quota.
# - "sklearn": constant-feature visits CONSUME the max_features quota,
#   replicating sklearn 1.0.2 _splitter.pyx node_split exactly
#   (n_visited_features counts drawn-known-constant and found-constant
#   features alike; the visit loop extends past the quota only until the
#   first non-constant). Round-4 parity isolation RULED THIS OUT as the RF
#   ensemble deviation mechanism: the no-SMOTE diagnostic config reads
#   +0.0721 under this arm vs +0.0703 under "informative" (6 seeds, 64
#   bins) — no movement — so the default stays the arm the ET parity
#   record was validated under.
FEATURE_QUOTA = os.environ.get("F16_FEATURE_QUOTA", "informative")
if FEATURE_QUOTA not in ("sklearn", "informative"):
    raise ValueError(
        f"F16_FEATURE_QUOTA must be sklearn|informative, got {FEATURE_QUOTA!r}"
    )


def _select_features(nc, key, max_features):
    """sklearn splitter feature sampling: visit features in uniform-random
    order and return the non-constant ones in the visited prefix (see
    FEATURE_QUOTA above for what bounds the prefix).

    nc: [W, F] bool — feature non-constant within node.
    ``key`` is either one uint32 key [2] (one draw covering all rows) or
    per-row keys [W, 2]; the hist grower passes per-node keys derived from
    global node ids so the node-batch width stays results-neutral.
    Returns sel [W, F] bool; empty rows (no informative feature) leaf out
    in the caller via the -inf score path.
    """
    if max_features is None:
        return nc
    if key.ndim == 2:
        u = jax.vmap(lambda k: jax.random.uniform(k, nc.shape[1:]))(key)
    else:
        u = jax.random.uniform(key, nc.shape)
    if FEATURE_QUOTA == "informative":
        r = jnp.where(nc, u, jnp.inf)
        kth = jnp.sort(r, axis=1)[:, max_features - 1 : max_features]
        return (r <= kth) & nc
    # "sklearn": visit order = rank of u; the visited prefix is
    # max_features long, extended to reach the first non-constant when the
    # quota's worth of visits were all constants. Selected = non-constant
    # in prefix. All-constant rows select nothing (the caller leafs).
    f = nc.shape[1]
    rank = jnp.argsort(jnp.argsort(u, axis=1), axis=1)
    minrank_nc = jnp.min(jnp.where(nc, rank, f), axis=1, keepdims=True)
    prefix = jnp.maximum(max_features, minrank_nc + 1)
    return nc & (rank < prefix)


def _run_boundaries(s_rel):
    """Per sorted position: (is_start, is_end) masks of its (contiguous)
    node run. s_rel [..., N] is sorted; runs are maximal equal stretches."""
    is_start = jnp.concatenate(
        [jnp.ones_like(s_rel[..., :1], bool),
         s_rel[..., 1:] != s_rel[..., :-1]], axis=-1
    )
    is_end = jnp.concatenate(
        [s_rel[..., 1:] != s_rel[..., :-1],
         jnp.ones_like(s_rel[..., :1], bool)], axis=-1
    )
    return is_start, is_end


def _prefix_stats(vals, is_start, is_end):
    """(within-run inclusive prefix sum, run total) for ``vals`` [..., N].

    ``vals`` must be nonnegative: its cumsum ``c`` is then nondecreasing, so
    the value of ``c`` just before each run start (and at each run end) can
    be propagated across the run with cummax scans instead of the
    take_along_axis gathers TPUs serialize.
    """
    c = jnp.cumsum(vals, axis=-1)
    c_prev = jnp.concatenate([jnp.zeros_like(c[..., :1]), c[..., :-1]],
                             axis=-1)
    axis = c.ndim - 1
    # latest start at-or-before i has the largest c_prev among starts;
    # nearest end at-or-after i has the smallest c among ends
    before = lax.cummax(jnp.where(is_start, c_prev, -jnp.inf), axis=axis)
    at_end = lax.cummin(
        jnp.where(is_end, c, jnp.inf), axis=axis, reverse=True
    )
    return c - before, at_end - before


def _segmented_suffix_best(seg, score, n):
    """For each position i: (max score, min position among maxima) over
    [i .. end of i's run]. Associative segmented suffix scan — the
    scatter-free replacement for per-node segment_max/segment_argmax."""
    pos = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), score.shape)

    def comb(a, b):
        # After the flip, ``a`` accumulates the ORIGINAL-order suffix of
        # ``b``'s position; keep b's key, merging stats only within a run.
        ka, sa, pa = a
        kb, sb, pb = b
        same = ka == kb
        better = same & ((sa > sb) | ((sa == sb) & (pa < pb)))
        return (kb, jnp.where(better, sa, sb), jnp.where(better, pa, pb))

    flipped = jax.tree.map(
        lambda t: jnp.flip(t, -1), (seg, score, pos)
    )
    _, s, p = lax.associative_scan(comb, flipped, axis=score.ndim - 1)
    return jnp.flip(s, -1), jnp.flip(p, -1)


def _node_lookup(sample_rel, w_cap):
    """Each dense node slot's run-start position in the (node, value)-sorted
    order, computed ONCE per level from the raw rel ids.

    Runs appear in node order inside every feature's sorted array (stable
    sort by the same per-sample rel-id multiset), so slot j's run start is
    simply the count of samples in lower-id slots — a bincount + exclusive
    cumsum, shared by all features. This replaces a per-feature vmapped
    ``searchsorted`` that profiling showed TPUs lower to a 2.8-second
    gather loop at [60 trees x 16 features x 1000 samples].

    Returns (pos [W], pos_end [W] — run start/end positions, int32 clamped
    in-bounds — and present [W] bool).
    """
    n = sample_rel.shape[0]
    count = jnp.sum(
        sample_rel[:, None] == jnp.arange(w_cap, dtype=jnp.int32)[None, :],
        axis=0, dtype=jnp.int32,
    )
    pos = _exclusive_cumsum(count)
    pos_end = jnp.clip(pos + count - 1, 0, n - 1)
    return jnp.minimum(pos, n - 1), pos_end, count > 0


def _window_update(arr, start, updates, mask):
    """Masked dynamic_update_slice: write ``updates`` [W] at [start, start+W),
    preserving existing contents where ``mask`` is False. ``arr`` must be
    padded so the window is always in bounds (no XLA start clamping)."""
    w = updates.shape[0]
    old = lax.dynamic_slice_in_dim(arr, start, w)
    merged = jnp.where(mask, updates.astype(arr.dtype), old)
    return lax.dynamic_update_slice_in_dim(arr, merged, start, axis=0)


def _emit_children(can_split, lw_b, lwy_b, tot_w_b, tot_wy_b):
    """Cover values for the 2k children created by a batch of splits.

    Child slot s in [0, 2k): rank r = s//2; the monotone split-rank is
    inverted with searchsorted to find the r-th splitting parent slot.
    Returns (child_vals [2W, 2], child_ok [2W], j_safe [2W] — each child's
    parent slot, for extra per-child metadata like depth). Shared by both
    growers so the BFS id-allocation invariants have one source of truth.
    """
    w_cap = can_split.shape[0]
    child_slots = jnp.arange(2 * w_cap, dtype=jnp.int32)
    r_of_slot = child_slots // 2
    csum = jnp.cumsum(can_split.astype(jnp.int32))
    j_of_slot = jnp.searchsorted(
        csum, r_of_slot + 1, side="left"
    ).astype(jnp.int32)
    j_safe = jnp.minimum(j_of_slot, w_cap - 1)
    is_right = (child_slots % 2) == 1
    lw_s = lw_b[j_safe]
    lwy_s = lwy_b[j_safe]
    tw_s = tot_w_b[j_safe]
    twy_s = tot_wy_b[j_safe]
    cw_s = jnp.where(is_right, tw_s - lw_s, lw_s)
    cwy_s = jnp.where(is_right, twy_s - lwy_s, lwy_s)
    child_ok = child_slots < 2 * csum[-1]
    child_vals = jnp.stack([cw_s - cwy_s, cwy_s], axis=-1)
    return child_vals, child_ok, j_safe


def _fit_one_tree(x, y01, w, key, order0, xsorted, *, random_splits,
                  max_features, max_depth, max_nodes):
    """Grow one tree level-by-level (see module docstring). Node arrays are
    padded by 2*W so every window write is statically in bounds; the caller
    slices back to max_nodes. Returns Forest field arrays."""
    n, n_feat = x.shape
    dt = x.dtype
    w_cap = n            # frontier rel-ids live in [0, n); n = parked
    park = jnp.int32(w_cap)
    m_pad = max_nodes + 2 * w_cap

    feature = jnp.full((m_pad,), -1, jnp.int32)
    threshold = jnp.zeros((m_pad,), dt)
    left = jnp.full((m_pad,), -1, jnp.int32)
    right = jnp.full((m_pad,), -1, jnp.int32)
    value = jnp.zeros((m_pad, 2), dt)

    wy = w * y01
    live = w > 0
    sample_rel = jnp.where(live, 0, w_cap).astype(jnp.int32)
    # Per-tree weights pre-gathered into each feature's value order, hoisted
    # out of the level loop (w is constant per tree) so the level sort can
    # carry them as payloads instead of re-gathering.
    w_f = w[order0]
    wy_f = wy[order0]
    # Root cover (the only node not created as a child of a split).
    tot_w0, tot_wy0 = jnp.sum(w), jnp.sum(wy)
    value = value.at[0].set(jnp.stack([tot_w0 - tot_wy0, tot_wy0]))

    def level(state):
        (feature, threshold, left, right, value, n_nodes, level_base,
         sample_rel, d) = state
        kf, kt = jax.random.split(jax.random.fold_in(key, d))

        # ---- sorted (node, value) order per feature -----------------------
        # One stable multi-operand sort carries all payloads (value and the
        # per-tree weights pre-gathered into value order outside the loop),
        # replacing argsort + four take_along_axis gathers.
        key_f = sample_rel[order0]                      # [F, N]
        s_rel, s_val, s_w, s_wy = lax.sort(
            (key_f, xsorted, w_f, wy_f), dimension=1, is_stable=True,
            num_keys=1,
        )

        is_start, is_end = _run_boundaries(s_rel)
        lw_pre, tot_w = _prefix_stats(s_w, is_start, is_end)
        lwy_pre, tot_wy = _prefix_stats(s_wy, is_start, is_end)
        # run start/end positions are level-shared across features ([W])
        pos_j, pos_end_j, present = _node_lookup(sample_rel, w_cap)

        active = s_rel < park
        v_next = jnp.concatenate(
            [s_val[:, 1:], s_val[:, -1:]], axis=-1
        )

        def gather_j(a, idx=None):                      # [F, N] -> [F, W]
            return jnp.take(a, pos_j if idx is None else idx, axis=-1)

        tot_w_j = gather_j(tot_w)
        tot_wy_j = gather_j(tot_wy)
        v_lo_j = gather_j(s_val)                        # run start = node min
        v_hi_j = gather_j(s_val, pos_end_j)             # run end = node max
        nc_j = present[None, :] & (v_hi_j - v_lo_j > FEATURE_EPS)

        if random_splits:
            # ExtraTrees: one uniform threshold per (feature, node) in
            # [node_min, node_max); left mass via prefix sums of the left
            # indicator (values are sorted within a run, so the indicator is
            # a prefix and its run totals are exact).
            u = jax.random.uniform(kt, (n_feat, w_cap), dtype=dt)
            thr_j = v_lo_j + u * (v_hi_j - v_lo_j)
            thr_j = jnp.where(thr_j >= v_hi_j, v_lo_j, thr_j)  # sklearn guard
            thr_s = jnp.take_along_axis(
                thr_j, jnp.minimum(s_rel, w_cap - 1), axis=-1
            )
            left_i = (s_val <= thr_s) & active
            _, lw_tot = _prefix_stats(
                jnp.where(left_i, s_w, 0.0), is_start, is_end
            )
            _, lwy_tot = _prefix_stats(
                jnp.where(left_i, s_wy, 0.0), is_start, is_end
            )
            lw_j = gather_j(lw_tot)
            lwy_j = gather_j(lwy_tot)
            valid_j = nc_j & (lw_j > 0) & (tot_w_j - lw_j > 0)
            score_j = _proxy_score(
                lw_j, lwy_j, tot_w_j - lw_j, tot_wy_j - lwy_j, valid_j
            )
            lw_best_src, lwy_best_src = lw_j, lwy_j
        else:
            # Exact best splits: every between-values position in a run is a
            # candidate; leftmost-best via a segmented suffix scan.
            rw = tot_w - lw_pre
            rwy = tot_wy - lwy_pre
            valid = (
                active
                & ~is_end
                & (v_next - s_val > FEATURE_EPS)
                & (lw_pre > 0)
                & (rw > 0)
            )
            score_i = _proxy_score(lw_pre, lwy_pre, rw, rwy, valid)
            best_s, best_p = _segmented_suffix_best(s_rel, score_i, n)
            score_j = gather_j(best_s)
            bpos_j = gather_j(best_p)
            v_lo = jnp.take_along_axis(s_val, bpos_j, axis=-1)
            v_hi = jnp.take_along_axis(v_next, bpos_j, axis=-1)
            thr_j = (v_lo + v_hi) / 2.0
            thr_j = jnp.where(thr_j == v_hi, v_lo, thr_j)  # midpoint guard
            lw_best_src = jnp.take_along_axis(lw_pre, bpos_j, axis=-1)
            lwy_best_src = jnp.take_along_axis(lwy_pre, bpos_j, axis=-1)
            score_j = jnp.where(jnp.isfinite(score_j), score_j, -jnp.inf)

        # ---- choose feature per node (sklearn random feature draw) --------
        sel = _select_features(nc_j.transpose(1, 0), kf, max_features)
        score_j = jnp.where(sel.transpose(1, 0), score_j, -jnp.inf)
        best_f = jnp.argmax(score_j, axis=0).astype(jnp.int32)      # [W]
        best_score = jnp.max(score_j, axis=0)

        def pick_f(a):                                   # [F, W] -> [W]
            return jnp.take_along_axis(a, best_f[None, :], axis=0)[0]

        thr_node = pick_f(thr_j)
        lw_b = pick_f(lw_best_src)
        lwy_b = pick_f(lwy_best_src)
        tot_w_b = pick_f(tot_w_j)
        tot_wy_b = pick_f(tot_wy_j)

        impure = (tot_wy_b > 0) & (tot_w_b - tot_wy_b > 0)
        can_split = jnp.isfinite(best_score) & impure & present
        rank = _exclusive_cumsum(can_split.astype(jnp.int32))
        left_g = n_nodes + 2 * rank
        right_g = left_g + 1
        can_split = can_split & (right_g < max_nodes)    # capacity guard
        k_splits = jnp.sum(can_split, dtype=jnp.int32)

        # ---- frontier window writes (contiguous ids, no scatter) ----------
        feature = _window_update(
            feature, level_base, jnp.where(can_split, best_f, -1), can_split
        )
        threshold = _window_update(
            threshold, level_base, thr_node, can_split
        )
        left = _window_update(
            left, level_base, jnp.where(can_split, left_g, -1), can_split
        )
        right = _window_update(
            right, level_base, jnp.where(can_split, right_g, -1), can_split
        )

        # ---- child cover values, written at creation ----------------------
        child_vals, child_ok, _ = _emit_children(
            can_split, lw_b, lwy_b, tot_w_b, tot_wy_b
        )
        value = _window_update(value, n_nodes, child_vals, child_ok[:, None])

        # ---- route samples to children / park finished nodes --------------
        rel_safe = jnp.minimum(sample_rel, w_cap - 1)
        splits_mine = can_split[rel_safe] & (sample_rel < park)
        bf_mine = best_f[rel_safe]
        xv = jnp.take_along_axis(x, bf_mine[:, None], axis=1)[:, 0]
        go_left = xv <= thr_node[rel_safe]
        child_rel = 2 * rank[rel_safe] + jnp.where(go_left, 0, 1)
        sample_rel = jnp.where(
            splits_mine, child_rel, park
        ).astype(jnp.int32)

        return (feature, threshold, left, right, value,
                n_nodes + 2 * k_splits, n_nodes, sample_rel, d + 1)

    def cond(state):
        n_nodes, level_base, d = state[5], state[6], state[8]
        return (d < max_depth) & (n_nodes > level_base)

    state = (feature, threshold, left, right, value, jnp.int32(1),
             jnp.int32(0), sample_rel, jnp.int32(0))
    state = lax.while_loop(cond, level, state)
    feature, threshold, left, right, value = state[:5]
    n_nodes = state[5]

    return (feature[:max_nodes], threshold[:max_nodes], left[:max_nodes],
            right[:max_nodes], value[:max_nodes], n_nodes)


# --------------------------------------------------------------------------
# Histogram grower v2 — one batched program per config, three formulations.
#
# The exact grower above is sort/gather-bound: profiling on TPU v5e shows
# >80% of fit time in `searchsorted` lowerings and `take_along_axis` gathers,
# which TPUs execute serially (~14 ms per [60,16,1000] gather). The fast
# tier therefore uses the classic histogram formulation (LightGBM-style):
# features are quantile-binned ONCE per config, per-node class histograms
# come from one contraction per step
#     H[f, node, bin] = sum_n onehot_node[n, node] * w[n] * onehot_bin[n, f, b]
# and split scores from cumulative sums over the bin axis. Sibling
# histograms are never rebuilt: the scan over boundaries IS the left-child
# histogram, and every right-side count is the subtraction  R = total - L
# (`hist_subtract`; child covers reuse the winning boundary's L the same
# way) — the one-pass analog of LightGBM's smaller-side trick under static
# shapes.
#
# The histogram+scan step has one resolved implementation (`hist_impl`,
# canonicalized by ``fit_forest_hist``):
#
# - "xla" (CPU default): ONE packed-f32 one-hot matmul. w and w*y are
#   packed as w + _PACK*wy per sample; the [N,W]x[N,F*B] contraction and
#   the bin cumsum run once on the packed value, and (cw, cwy) unpack by
#   floor-divide. Integer-exact while per-node weight sums stay < _PACK
#   (gated on N; falls back to "einsum" above it). Replaced the round-2
#   scatter formulation (`segment_sum`, still accepted as the alias
#   "segsum"): XLA:CPU scatters cost ~36 ns/element serially, and at the
#   bench shape the packed matmul measures ~10x faster (_scratch/micro_fit:
#   232 ms -> 12 ms per step-equivalent at N=400 F=16 B=64 W=8 T=250).
# - "einsum" (TPU fallback rung): the same contraction as a PAIR of bf16
#   one-hot matmuls (weights are small integers — exact in bf16 operands
#   with f32 accumulation), then two bin cumsums. Pure MXU work.
# - "pallas" (TPU default): one kernel fusing the two bf16 dots and the
#   bin cumsum in VMEM, f-blocked grid (_hist_cumsum_kernel). Bitwise
#   equal to "einsum" by construction (test-pinned in interpret mode);
#   `resilience.ladder` degrades pallas -> einsum ("hist" kernel rung) on
#   the first Mosaic failure, mirroring the Tree SHAP pallas -> xla rung.
#
# All three produce identical [F, W, B] cumulative histograms, so scoring,
# feature choice, routing, and RNG are impl-independent: forests depend
# only on data + key (impl/backend/width-neutral).
#
# Growth is node-batched rather than level-synchronous: BFS allocation makes
# node ids contiguous in creation order, so the work queue is just a pointer
# pair (P = next unprocessed id, A = next free id) and each iteration
# processes the id window [P, P+W). Iteration count is ceil(total_nodes / W)
# — proportional to tree size, not depth x frontier like the exact grower.
#
# Parity tier: bin-resolution candidate SELECTION is kept, but the winning
# threshold is sharpened in-step to the exact sklearn midpoint between the
# closest member values either side of the chosen bin edge (refine="exact",
# an O(N x W) masked reduce — no sort). Member (in-bag) routing is
# provably unchanged (max-left <= edge < min-right over members), so
# refinement moves only the stored threshold for the grown tree;
# out-of-bag and held-out rows may legitimately land on the other side of
# the sharpened threshold — that freedom is what moves held-out F1 toward
# sklearn (test_hist_refine_exact_moves_only_thresholds pins both
# properties); with it the SAME grower tier serves the RF/ET ensembles inside
# the RF-probe parity budget (BASELINE.md F1 +/- 0.01) and carries the
# bench number. Single-tree DT stays on the exact grower (no ensemble
# averaging — DT-on-hist diverged -0.066 on the small parity tier), and
# the exact grower also remains as the ensembles' `grower="exact"`
# fallback tier.
# ExtraTrees randomness: sklearn draws thresholds uniformly over the node's
# value range; here the draw is uniform in VALUE space over the node's
# occupied bin span, rounded to bin resolution (F16_ET_DRAW=rank restores
# the round-2 boundary-index draw), and refinement does not apply (sklearn
# ET thresholds are draws, not midpoints).
# --------------------------------------------------------------------------

# Histogram-grower tuning knobs. Env-overridable (read at import) so the
# hardware tuning sweep (tools/hw_probe.py "tune_hist") can vary them per
# subprocess without code edits; defaults are the shipped configuration.
HIST_BINS = int(os.environ.get("F16_HIST_BINS", "64"))
# Node-batch width of the hist grower's BFS step, per backend: the MXU
# wants wide one-hot matmuls (128 untuned pending hardware time); CPU pays
# per-step cost proportional to the batch width (segment space + padded
# slots) but per-TREE cost proportional to the step count, so the CPU
# sweet spot is shape-dependent — measured: 25-tree x 10-fold chunk at
# N=400/max_nodes=1600: 4 -> 1.76 s, 8 -> 1.68 s, 16 -> 2.72 s,
# 32 -> 4.98 s; the production dryrun shape (N=1000, max_nodes=4000) is
# ~25% faster at 16 than 8. Widths are results-neutral (per-node RNG keys
# derive from global node ids; any width grows the same forest), so the
# CPU width auto-selects by max_nodes; a nonzero F16_HIST_NODE_BATCH_CPU
# pins it.
HIST_NODE_BATCH = int(os.environ.get("F16_HIST_NODE_BATCH", "128"))
HIST_NODE_BATCH_CPU = int(os.environ.get("F16_HIST_NODE_BATCH_CPU", "0"))
# ExtraTrees threshold-draw space in the hist grower: "value" (sklearn's
# uniform over the node's value range, rounded to bin resolution — the
# default since round 3's parity investigation) or "rank" (uniform over
# occupied boundary indices — the round-2 behavior). Unlike the width
# knobs this IS model-changing; it exists for the parity A/B.
ET_DRAW = os.environ.get("F16_ET_DRAW", "value")
if ET_DRAW not in ("value", "rank"):  # a typo'd A/B arm must fail loudly
    raise ValueError(f"F16_ET_DRAW must be value|rank, got {ET_DRAW!r}")
# Threshold refinement of the winning split (RF/DT; see section comment):
# "exact" sharpens to sklearn midpoints in-step, "edge" keeps the raw bin
# edge (the pre-v2 behavior — the parity A/B arm).
HIST_REFINE = os.environ.get("F16_HIST_REFINE", "exact")
if HIST_REFINE not in ("exact", "edge"):
    raise ValueError(
        f"F16_HIST_REFINE must be exact|edge, got {HIST_REFINE!r}")
# Sample-tile size of the exact-refinement reduce (refine="exact" only):
# 0 runs the one-shot [N, W] masked max/min (the pre-tuner behavior); a
# positive tile streams the same reduce over clamp-overlapped sample tiles
# via fori_loop, bounding the materialized mask to [tile, W]. Overlap is
# harmless (max/min are idempotent), so every tile size grows the
# bit-identical forest — a pure perf/memory knob the tuner searches.
HIST_REFINE_TILE = int(os.environ.get("F16_HIST_REFINE_TILE", "0"))
# Histogram implementation override; "auto" resolves per backend + ladder
# ("segsum" is the accepted alias for what is now the "xla" formulation).
HIST_IMPL = os.environ.get("F16_HIST_IMPL", "auto")
if HIST_IMPL not in ("auto", "xla", "einsum", "pallas", "segsum"):
    raise ValueError(
        f"F16_HIST_IMPL must be auto|xla|einsum|pallas|segsum, "
        f"got {HIST_IMPL!r}")

# Packing radix of the "xla" formulation: per-node sums of w and w*y each
# stay < _PACK, so w + _PACK*wy accumulates both classes in one f32 matmul
# with every intermediate < _PACK + _PACK^2 < 2^24 (f32-exact).
_PACK = 2048.0


def _canon_hist_impl(impl):
    return "xla" if impl == "segsum" else impl


def hist_tier_default(n_trees=None):
    """Whether the grower tier selects the histogram grower for a config
    with ``n_trees`` trees — "hist" unless F16_ENSEMBLE_GROWER=exact (read
    at call time, matching parallel/sweep.py's per-config read). Shared by
    the serving/SHAP fit call sites so every layer follows one tier rule.
    Single-tree DT stays on the exact grower even under the hist tier:
    without ensemble averaging, bin-granular candidate ranking diverged
    −0.066 on the small parity tier, and one exact tree is never the fit
    bottleneck. ``n_trees=None`` means "an ensemble" (env check only)."""
    if n_trees is not None and n_trees <= 1:
        return False
    return os.environ.get("F16_ENSEMBLE_GROWER", "hist") == "hist"


def _auto_hist_impl():
    if jax.default_backend() != "tpu":
        return "xla"
    return "einsum" if _res_ladder.pallas_broken("hist") else "pallas"


def _cpu_node_batch(max_nodes):
    if HIST_NODE_BATCH_CPU:
        return HIST_NODE_BATCH_CPU
    # Width swept on the REAL bench configs (prof_fit --engine-only,
    # F16_HIST_NODE_BATCH_CPU in {4,8,16,32}): 8 wins the total (11.7 s
    # ensembles vs 14.3/15.2/25.6). bw=4 looks ~14% better on a small
    # synthetic RF shape but regresses the node-heavy PCA/SMOTE-Tomek ET
    # config 42% (steps ~ n_nodes / W, and that config grows near the
    # node cap). Width is a pure perf knob: any value grows the
    # bit-identical forest.
    return 8 if max_nodes <= 1600 else 16


def fit_stage_flops(*, n, n_feat, n_bins, n_trees, n_nodes, max_nodes,
                    node_batch=None):
    """Analytic per-stage flop model of one hist-grower fit (host-side,
    no tracing): {"bin", "hist_build", "split_scan", "partition"} flop
    counts for ``n_trees`` growths of ``n_nodes``-node trees.

    Three consumers share it (so the attribution story has ONE model):
    ``report --attrib`` splits the measured fit wall proportionally to
    these counts (flops-weighted — stages inside one fused dispatch are
    not separately timeable); bench.py's ``fit_gflops`` gate metric is
    their total over the fit wall; tools/prof_fit.py prints the same
    split against its direct kernel walls. Estimates, not op counts —
    the RELATIVE weights are what attribution needs, so each term keeps
    only its leading shape factor (the v2 matmul formulation):

    - bin: one-time quantile binning, n x F x B one-hot expansion;
    - hist_build: per window step, the [n, bw] x [n, F*B] one-hot
      contraction (2 flops per MAC on the packed operand);
    - split_scan: per step, cumsum + gini proxy + argmax/extract over
      the [F, bw, B] histogram space (~12 passes);
    - partition: per step, the O(n x bw) membership one-hot plus
      refinement reduces, and O(n) routing gathers.
    """
    if node_batch is None:
        node_batch = (_cpu_node_batch(max_nodes)
                      if jax.default_backend() == "cpu"
                      else HIST_NODE_BATCH)
    bw = max(1, min(node_batch, max_nodes))
    steps = max(1, -(-int(n_nodes) // bw))
    per_tree = {
        "bin": float(n * n_feat * n_bins) / max(1, n_trees),  # shared once
        "hist_build": float(steps * 2 * n * bw * n_feat * n_bins),
        "split_scan": float(steps * 12 * n_feat * bw * n_bins),
        "partition": float(steps * (4 * n * bw + 6 * n)),
    }
    return {k: round(v * n_trees, 1) for k, v in per_tree.items()}


def quantile_edges(x, n_bins=HIST_BINS):
    """Per-feature inner bin edges [F, n_bins-1]: midpoints between adjacent
    sorted values at quantile ranks (the histogram analog of sklearn's
    midpoint thresholds). Bin b covers edges[b-1] < x <= edges[b]."""
    n, _ = x.shape
    xs = jnp.sort(x, axis=0)
    ks = jnp.clip((jnp.arange(1, n_bins) * n) // n_bins - 1, 0, n - 1)
    lo = xs[ks]
    hi = xs[jnp.clip(ks + 1, 0, n - 1)]
    return ((lo + hi) * 0.5).T


def _bin_onehot(x, edges, dtype=jnp.bfloat16):
    """(onehot [N, F, B] ``dtype``, bin_idx [N, F] i32) for inner ``edges``
    [F, B-1]; bin index is the count of edges strictly below x. The xla
    formulation contracts in f32 (packed weights), the MXU ones in bf16."""
    cmp = x[:, :, None] > edges[None, :, :]
    bin_idx = cmp.sum(-1).astype(jnp.int32)
    n_bins = edges.shape[1] + 1
    oh = jax.nn.one_hot(bin_idx, n_bins, dtype=dtype)
    return oh, bin_idx


def hist_subtract(total, side):
    """Sibling histogram by subtraction — counts are small integers, exact
    in f32, so R = total - L loses nothing. Trivial on purpose: it is the
    load-bearing identity of the grower (every right-side statistic in the
    split scan and every right-child cover derives from it; nothing ever
    rebuilds a sibling histogram from samples), named so the property test
    (tests/test_trees.py) pins it against a from-scratch rebuild."""
    return total - side


def _hist_cumsum_kernel(ohw_ref, ohwy_ref, ohfb_ref, cw_ref, cwy_ref):
    """One feature's cumulative class histograms: two bf16 [N,W]x[N,B] dots
    (contract the sample axis) + the bin cumsum, all in VMEM."""
    oh = ohfb_ref[0]                                   # [N, B]
    dn = (((0,), (0,)), ((), ()))
    hw = lax.dot_general(ohw_ref[...], oh, dn,
                         preferred_element_type=jnp.float32)
    hwy = lax.dot_general(ohwy_ref[...], oh, dn,
                          preferred_element_type=jnp.float32)
    cw_ref[0] = jnp.cumsum(hw, axis=-1)
    cwy_ref[0] = jnp.cumsum(hwy, axis=-1)


def _pallas_cum_hists(ohw, ohwy, ohfb):
    """(cw, cwy) [F, W, B] f32 cumulative histograms from bf16 one-hots
    (ohw/ohwy [N, W], ohfb [N, F, B]); f-blocked grid so each program's
    working set is one feature's [N, B] one-hot plus the shared [N, W]
    membership — sized for VMEM at sweep shapes. Interpret mode runs the
    same ops through XLA off-TPU, which is what pins bitwise equality
    with the "einsum" formulation."""
    n, w = ohw.shape
    _, f, b = ohfb.shape
    return tuple(pl.pallas_call(
        _hist_cumsum_kernel,
        grid=(f,),
        in_specs=[
            pl.BlockSpec((n, w), lambda i: (0, 0)),
            pl.BlockSpec((n, w), lambda i: (0, 0)),
            pl.BlockSpec((1, n, b), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, w, b), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, w, b), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((f, w, b), jnp.float32),
            jax.ShapeDtypeStruct((f, w, b), jnp.float32),
        ],
        interpret=jax.default_backend() != "tpu",
    )(ohw, ohwy, ohfb.transpose(1, 0, 2)))


def _refine_minmax(act, go_left, xv, tile):
    """(max-left, min-right) [W] of the exact-refinement reduce: per window
    node, the largest member value routed left and the smallest routed
    right. ``tile`` 0 (or >= N) materializes the one-shot [N, W] masks; a
    positive tile streams the identical reduce over ``tile``-row sample
    slices (the last tile clamps back, overlapping rows already reduced —
    idempotent under max/min), so every tile size is bitwise-equal to the
    one-shot path and the knob is pure perf/memory."""
    n, bw = act.shape
    def onestep(a, g, v):
        m_l = jnp.max(jnp.where(a & g[:, None], v[:, None], -jnp.inf),
                      axis=0)
        m_r = jnp.min(jnp.where(a & ~g[:, None], v[:, None], jnp.inf),
                      axis=0)
        return m_l, m_r
    if not tile or tile >= n:
        return onestep(act, go_left, xv)

    def body(i, carry):
        m_l, m_r = carry
        s = jnp.minimum(i * tile, n - tile)
        t_l, t_r = onestep(
            lax.dynamic_slice_in_dim(act, s, tile),
            lax.dynamic_slice_in_dim(go_left, s, tile),
            lax.dynamic_slice_in_dim(xv, s, tile),
        )
        return jnp.maximum(m_l, t_l), jnp.minimum(m_r, t_r)

    init = (jnp.full((bw,), -jnp.inf, xv.dtype),
            jnp.full((bw,), jnp.inf, xv.dtype))
    return lax.fori_loop(0, -(-n // tile), body, init)


def _fit_one_tree_hist(x, ohfb, bin_idx, edges, y01, w, key, *, random_splits,
                       max_features, max_depth, max_nodes, node_batch,
                       hist_impl, refine, refine_tile):
    """Grow one tree from binned features. Returns Forest field arrays
    (same contract as ``_fit_one_tree``). ``hist_impl`` arrives resolved
    and canonical ("xla" | "einsum" | "pallas"); ``node_batch`` is the BFS
    window width (results-neutral — per-node RNG keys derive from global
    node ids); ``refine`` ("exact" | "edge") picks whether the winning
    threshold is sharpened to the exact sklearn midpoint in-step."""
    n, n_feat, n_bins = ohfb.shape
    dt = edges.dtype
    bw = min(node_batch, max_nodes)            # node-batch width
    m_pad = max_nodes + 2 * bw
    iota_w = jnp.arange(bw, dtype=jnp.int32)

    feature = jnp.full((m_pad,), -1, jnp.int32)
    threshold = jnp.zeros((m_pad,), dt)
    left = jnp.full((m_pad,), -1, jnp.int32)
    right = jnp.full((m_pad,), -1, jnp.int32)
    value = jnp.zeros((m_pad, 2), dt)
    depth = jnp.zeros((m_pad,), jnp.int32)

    wy = w * y01
    pw = w + _PACK * wy                        # packed pair ("xla" impl only)
    sample_node = jnp.where(w > 0, 0, -1).astype(jnp.int32)
    tot_w0, tot_wy0 = jnp.sum(w), jnp.sum(wy)
    value = value.at[0].set(jnp.stack([tot_w0 - tot_wy0, tot_wy0]))

    def step(state):
        (feature, threshold, left, right, value, depth, a, p,
         sample_node) = state
        # Per-NODE keys from global node ids — not from the window start —
        # so the node-batch width is a pure perf knob: any width grows the
        # same forest from the same ``key``.
        nkeys = jax.vmap(lambda d: jax.random.fold_in(key, d))(p + iota_w)
        ksplit = jax.vmap(jax.random.split)(nkeys)     # [W, 2, 2]
        kf, kt = ksplit[:, 0], ksplit[:, 1]

        # ---- membership + cumulative class histograms ---------------------
        # Three formulations of the same [F, W, B] cumulative histograms
        # (section comment above); weights are small integers, all three
        # accumulate exactly in f32 and agree bitwise.
        rel = sample_node - p                          # [N]
        inb = (rel >= 0) & (rel < bw)
        onehot = (rel[:, None] == iota_w[None, :]) & inb[:, None]   # [N, W]
        if hist_impl == "xla":
            opw = onehot * pw[:, None]                 # [N, W] packed f32
            c = jnp.cumsum(
                jnp.einsum("nw,nfb->fwb", opw, ohfb,
                           preferred_element_type=jnp.float32), axis=-1)
            cwy = jnp.floor(c * (1.0 / _PACK))
            cw = c - _PACK * cwy
        else:
            ohw = (onehot * w[:, None]).astype(jnp.bfloat16)
            ohwy = (onehot * wy[:, None]).astype(jnp.bfloat16)
            if hist_impl == "pallas":
                cw, cwy = _pallas_cum_hists(ohw, ohwy, ohfb)
            else:                                      # "einsum"
                cw = jnp.cumsum(
                    jnp.einsum("nw,nfb->fwb", ohw, ohfb,
                               preferred_element_type=jnp.float32), axis=-1)
                cwy = jnp.cumsum(
                    jnp.einsum("nw,nfb->fwb", ohwy, ohfb,
                               preferred_element_type=jnp.float32), axis=-1)

        tot_w = cw[0, :, -1]                           # [W] (same for all f)
        tot_wy = cwy[0, :, -1]
        lw = cw[..., :-1]                              # boundary b -> [.., b-1]
        lwy = cwy[..., :-1]
        # every right-side statistic is histogram SUBTRACTION off the
        # cumulative left scan — siblings are never rebuilt from samples
        rw = hist_subtract(tot_w[None, :, None], lw)
        rwy = hist_subtract(tot_wy[None, :, None], lwy)
        valid = (lw > 0) & (rw > 0)                    # [F, W, B-1]
        nc = jnp.any(valid, axis=-1)                   # [F, W] non-constant
        edges_w = jnp.broadcast_to(edges[:, None, :], (n_feat, bw, n_bins - 1))

        if random_splits:
            # ExtraTrees: sklearn draws the threshold uniformly over the
            # node's VALUE range (the exact grower replicates it directly,
            # trees.py _fit_one_tree). Binned twin: the node's span comes
            # from its occupied bins' edge values (end bins extrapolate one
            # neighbor width), the draw is uniform in value space, and the
            # drawn value rounds down to its bin's lower boundary — so the
            # boundary distribution weights each bin by its VALUE width,
            # converging to sklearn's draw as bins densify. Round-3 parity
            # data motivated the switch: the rank-space draw (uniform over
            # boundary indices, value-width-blind; F16_ET_DRAW=rank
            # restores it) read low on the PCA probe config. All index
            # arithmetic stays in the tiny [F, W] space — occupancy comes
            # from cumsum increases, extraction is take_along_axis there.
            prev = jnp.concatenate(
                [jnp.zeros_like(cw[..., :1]), cw[..., :-1]], axis=-1)
            occ = cw > prev                            # [F, W, B] occupied
            lo = jnp.argmax(occ, axis=-1)              # [F, W]
            hi = n_bins - 1 - jnp.argmax(jnp.flip(occ, -1), axis=-1)
            u = jax.vmap(
                lambda k: jax.random.uniform(k, (n_feat,), dtype=dt)
            )(kt).T                                    # [F, W], per-node keys
            if ET_DRAW == "rank" or n_bins < 3:
                # (n_bins=2 has a single boundary — no width information to
                # weight; the rank draw is exact there anyway)
                span = jnp.maximum(hi - lo, 1)
                bsel = lo + 1 + jnp.floor(u * span).astype(jnp.int32)
            else:
                first = edges[:, :1] - (edges[:, 1:2] - edges[:, :1])
                last = edges[:, -1:] + (edges[:, -1:] - edges[:, -2:-1])
                full = jnp.concatenate([first, edges, last], 1)  # [F, B+1]
                fullw = jnp.broadcast_to(full[:, None, :],
                                         (n_feat, bw, n_bins + 1))
                vmin = jnp.take_along_axis(fullw, lo[..., None], -1)[..., 0]
                vmax = jnp.take_along_axis(fullw, (hi + 1)[..., None],
                                           -1)[..., 0]
                thr_v = vmin + u * (vmax - vmin)
                cnt = jnp.sum(edges[:, None, :] < thr_v[:, :, None],
                              axis=-1).astype(jnp.int32)
                bsel = jnp.clip(cnt, lo + 1, hi)
            # single-occupied-bin nodes can push bsel out of boundary range;
            # they are constant (nc False) so the clamp never changes a
            # selected split — it only keeps the extract indices in-bounds
            bm1 = jnp.clip(bsel - 1, 0, n_bins - 2)[..., None]
            lw_j = jnp.take_along_axis(lw, bm1, -1)[..., 0]
            lwy_j = jnp.take_along_axis(lwy, bm1, -1)[..., 0]
            ok_j = nc & (lw_j > 0) & (tot_w[None, :] - lw_j > 0)
            score_j = _proxy_score(lw_j, lwy_j, tot_w[None, :] - lw_j,
                                   tot_wy[None, :] - lwy_j, ok_j)
            bound_j = bsel
            thr_j = jnp.take_along_axis(edges_w, bm1, -1)[..., 0]
        else:
            score = _proxy_score(lw, lwy, rw, rwy, valid)   # [F, W, B-1]
            bb = jnp.argmax(score, axis=-1)            # first max = lowest thr
            bbx = bb[..., None]
            score_j = jnp.take_along_axis(score, bbx, -1)[..., 0]
            bound_j = bb + 1
            lw_j = jnp.take_along_axis(lw, bbx, -1)[..., 0]
            lwy_j = jnp.take_along_axis(lwy, bbx, -1)[..., 0]
            thr_j = jnp.take_along_axis(edges_w, bbx, -1)[..., 0]

        # ---- feature choice (sklearn random feature draw) -----------------
        sel = _select_features(nc.transpose(1, 0), kf, max_features)
        score_j = jnp.where(sel.transpose(1, 0), score_j, -jnp.inf)
        best_f = jnp.argmax(score_j, axis=0).astype(jnp.int32)     # [W]
        best_score = jnp.max(score_j, axis=0)
        bfx = best_f[None, :]

        def pick_f(a):                                  # [F, W] -> [W]
            return jnp.take_along_axis(a, bfx, axis=0)[0]

        thr_node = pick_f(thr_j).astype(dt)
        bound_n = pick_f(bound_j).astype(jnp.int32)
        lw_b = pick_f(lw_j)
        lwy_b = pick_f(lwy_j)

        # ---- split decision ----------------------------------------------
        present = iota_w < (a - p)
        dep = lax.dynamic_slice_in_dim(depth, p, bw)
        impure = (tot_wy > 0) & (tot_w - tot_wy > 0)
        can_split = (
            (best_score > -jnp.inf) & impure & present & (dep < max_depth)
        )
        rank = _exclusive_cumsum(can_split.astype(jnp.int32))
        left_g = a + 2 * rank
        right_g = left_g + 1
        can_split = can_split & (right_g < max_nodes)
        k_splits = jnp.sum(can_split, dtype=jnp.int32)

        # ---- per-sample node parameters (routing + refinement) ------------
        # Row gathers from tiny [W] tables on the xla impl (cheap on CPU);
        # a one-hot table matmul on the MXU impls (TPU serializes gathers).
        # Both yield each in-window sample's (splits?, child rank, bin
        # bound, winning feature's bin and value).
        if hist_impl == "xla":
            rs = jnp.clip(rel, 0, bw - 1)
            can_mine = inb & can_split[rs]
            rank_mine = rank[rs]
            bound_mine = bound_n[rs]
            bfx_mine = best_f[rs][:, None]
            xbin_mine = jnp.take_along_axis(bin_idx, bfx_mine, axis=1)[:, 0]
            xv_mine = jnp.take_along_axis(x, bfx_mine, axis=1)[:, 0]
        else:
            # table rows: [can_split, rank, bound] ++ onehot(best_f) — all
            # small integers, exact in bf16 with f32 accumulation.
            wdt = jnp.bfloat16
            table = jnp.concatenate(
                [can_split.astype(jnp.float32)[:, None],
                 rank.astype(jnp.float32)[:, None],
                 bound_n.astype(jnp.float32)[:, None],
                 jax.nn.one_hot(best_f, n_feat, dtype=jnp.float32)], axis=1,
            )
            route = jnp.einsum("nw,wc->nc", onehot.astype(wdt),
                               table.astype(wdt),
                               preferred_element_type=jnp.float32)
            can_mine = route[:, 0] > 0.5
            rank_mine = jnp.round(route[:, 1]).astype(jnp.int32)
            bound_mine = jnp.round(route[:, 2]).astype(jnp.int32)
            xbin_mine = jnp.round(
                jnp.sum(bin_idx.astype(jnp.float32) * route[:, 3:], -1)
            ).astype(jnp.int32)
            xv_mine = jnp.sum(x * route[:, 3:], -1)
        go_left = xbin_mine < bound_mine

        if refine == "exact" and not random_splits:
            # Sharpen each winner to the exact sklearn midpoint between the
            # closest member values either side of the chosen bin edge.
            # Routing is unchanged by construction — left members satisfy
            # x <= edge < x of right members, so maxL <= edge < minR and
            # the midpoint separates the same partition — hence structure,
            # covers, and leaf values are bit-identical to refine="edge";
            # only the stored threshold moves.
            act = onehot & can_mine[:, None]           # [N, W]
            mL, mR = _refine_minmax(act, go_left, xv_mine, refine_tile)
            mid = ((mL + mR) * 0.5).astype(dt)
            # sklearn's guard: a midpoint that rounds up to the right value
            # falls back to the left value (threshold rule is x <= thr)
            thr_ref = jnp.where(mid >= mR, mL, mid).astype(dt)
            ok_ref = jnp.isfinite(mL) & jnp.isfinite(mR) & can_split
            thr_node = jnp.where(ok_ref, thr_ref, thr_node)

        feature = _window_update(
            feature, p, jnp.where(can_split, best_f, -1), can_split
        )
        threshold = _window_update(threshold, p, thr_node, can_split)
        left = _window_update(
            left, p, jnp.where(can_split, left_g, -1), can_split
        )
        right = _window_update(
            right, p, jnp.where(can_split, right_g, -1), can_split
        )

        # ---- child covers + depth, written at creation --------------------
        # (the winning boundary's left stats + subtraction for the sibling,
        # inside _emit_children — covers are never recounted from samples)
        child_vals, child_ok, j_safe = _emit_children(
            can_split, lw_b, lwy_b, tot_w, tot_wy
        )
        value = _window_update(value, a, child_vals, child_ok[:, None])
        depth = _window_update(depth, a, dep[j_safe] + 1, child_ok)

        child_mine = a + 2 * rank_mine + jnp.where(go_left, 0, 1)
        sample_node = jnp.where(
            inb & can_mine, child_mine, jnp.where(inb, -1, sample_node)
        ).astype(jnp.int32)

        return (feature, threshold, left, right, value, depth,
                a + 2 * k_splits, jnp.minimum(p + bw, a), sample_node)

    def cond(state):
        a, p = state[6], state[7]
        return p < a

    state = (feature, threshold, left, right, value, depth, jnp.int32(1),
             jnp.int32(0), sample_node)
    state = lax.while_loop(cond, step, state)
    feature, threshold, left, right, value = state[:5]
    n_nodes = state[6]
    return (feature[:max_nodes], threshold[:max_nodes], left[:max_nodes],
            right[:max_nodes], value[:max_nodes], n_nodes)


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_trees", "bootstrap", "random_splits", "sqrt_features", "max_depth",
        "max_nodes", "tree_chunk", "n_bins", "hist_impl", "node_batch",
        "refine", "refine_tile",
    ),
)
def _fit_forest_hist_core(x, y, w, key, *, n_trees, bootstrap, random_splits,
                          sqrt_features, max_depth, max_nodes, tree_chunk,
                          n_bins, hist_impl, node_batch, refine, refine_tile,
                          edges=None, tree_keys=None):
    """The jitted grower program; every static is resolved by the
    ``fit_forest_hist`` wrapper. Instrumented below, so host dispatches
    emit ``cost`` events carrying the per-stage flop split."""
    n, f = x.shape
    max_features = max(1, int(f ** 0.5)) if sqrt_features else None
    if hist_impl == "xla" and n >= _PACK:
        # packed-f32 exactness needs per-node weight sums < _PACK; the bf16
        # pair keeps exactness (f32 accumulation) at any N
        hist_impl = "einsum"

    y01 = y.astype(x.dtype)
    w = w.astype(x.dtype)
    if edges is None:
        edges = quantile_edges(x, n_bins)
    oh_dt = jnp.float32 if hist_impl == "xla" else jnp.bfloat16
    ohfb, bin_idx = _bin_onehot(x, edges, dtype=oh_dt)

    keys = jax.random.split(key, n_trees) if tree_keys is None else tree_keys
    assert keys.shape[0] == n_trees, (keys.shape, n_trees)

    def one(k):
        kb, kg = jax.random.split(k)
        wt = _bootstrap_weights(w, kb) if bootstrap else w
        return _fit_one_tree_hist(
            x, ohfb, bin_idx, edges, y01, wt, kg,
            random_splits=random_splits, max_features=max_features,
            max_depth=max_depth, max_nodes=max_nodes,
            node_batch=node_batch, hist_impl=hist_impl, refine=refine,
            refine_tile=refine_tile,
        )

    feature, threshold, left, right, value, n_nodes = _map_trees(
        one, keys, n_trees, tree_chunk
    )
    return Forest(feature, threshold, left, right, value, n_nodes,
                  jnp.int32(max_depth))


def fit_forest_hist(x, y, w, key, *, n_trees, bootstrap, random_splits,
                    sqrt_features, max_depth=48, max_nodes=None,
                    tree_chunk=None, n_bins=HIST_BINS, edges=None,
                    tree_keys=None, hist_impl=None, node_batch=None,
                    refine=None, refine_tile=None):
    """Histogram-grower twin of ``fit_forest`` (same signature + ``n_bins``/
    ``edges``/``hist_impl``/``node_batch``/``refine``). ``edges``
    [F, n_bins-1] may be precomputed (e.g. once per config from the full
    preprocessed matrix, shared across folds); derived from ``x`` when
    None. Returns the same ``Forest`` structure, so predict and Tree SHAP
    are grower-agnostic.

    ``tree_keys`` [n_trees, 2] replaces the internal ``split(key, n_trees)``
    so callers can grow a forest across several device dispatches (slices of
    one key table) with bit-identical results — see sweep.py's
    dispatch-chunked path.

    ``hist_impl`` None resolves F16_HIST_IMPL, then auto by backend: "xla"
    off-TPU, "pallas" on TPU unless the resilience ladder has this kernel's
    pallas rung marked broken ("einsum"). A first-ever Mosaic failure under
    auto degrades pallas -> einsum HERE (host dispatches only — under an
    enclosing trace resolution is trace-time) and is remembered; an
    EXPLICIT "pallas" still raises. ``node_batch``/``refine``/
    ``refine_tile`` default from the backend width heuristic,
    F16_HIST_REFINE, and F16_HIST_REFINE_TILE; forests depend only on data
    + key (impl, width, and tile neutral — refine="edge" moves
    thresholds)."""
    if max_nodes is None:
        max_nodes = 2 * x.shape[0]
    if node_batch is None:
        node_batch = (_cpu_node_batch(max_nodes)
                      if jax.default_backend() == "cpu"
                      else HIST_NODE_BATCH)
    if refine is None:
        refine = HIST_REFINE
    if refine_tile is None:
        refine_tile = HIST_REFINE_TILE
    refine_tile = int(refine_tile)
    explicit = hist_impl if hist_impl is not None else (
        None if HIST_IMPL == "auto" else HIST_IMPL)
    impl = _canon_hist_impl(explicit) if explicit else _auto_hist_impl()
    if impl not in ("xla", "einsum", "pallas"):
        raise ValueError(f"unknown hist impl {impl!r}")

    def call(i):
        return _fit_forest_hist_core(
            x, y, w, key, n_trees=n_trees, bootstrap=bootstrap,
            random_splits=random_splits, sqrt_features=sqrt_features,
            max_depth=max_depth, max_nodes=max_nodes, tree_chunk=tree_chunk,
            n_bins=n_bins, hist_impl=i, node_batch=node_batch, refine=refine,
            refine_tile=refine_tile, edges=edges, tree_keys=tree_keys)

    if explicit or impl != "pallas":
        return call(impl)
    leaves = jax.tree_util.tree_leaves((x, y, w, key, edges, tree_keys))
    if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
        return call(impl)
    try:
        # block INSIDE the try: dispatch is async, so a Mosaic/runtime
        # fault would otherwise surface at the caller's sync (same shape
        # as treeshap's pallas -> xla rung)
        return jax.block_until_ready(call("pallas"))
    except Exception as e:  # Mosaic lowering/runtime errors share no base
        # the pallas -> einsum rung of the degradation ladder: classify,
        # emit the fault/degrade obs events, set the sticky per-kernel flag
        _res_ladder.mark_pallas_broken(e, kernel="hist")
        print(f"trees: hist pallas kernel failed on "
              f"{jax.default_backend()} ({type(e).__name__}: "
              f"{str(e)[:200]}); auto-falling back to hist_impl='einsum'",
              file=sys.stderr, flush=True)
        return call("einsum")


def _map_trees(one, keys, n_trees, tree_chunk):
    """vmap ``one`` over per-tree keys, optionally in sequential chunks of
    ``tree_chunk`` via ``lax.map`` (bounds the concurrent per-tree workspace;
    results are identical since keys don't depend on chunking).

    The degradation ladder's halvings apply here as a backstop rung
    (resilience/ladder.py): chunk-invariant, so a degraded re-trace grows
    identical trees in a smaller workspace. Trace-time only — callers
    inside a cached jit keep their compiled chunking until re-trace; the
    sweep's per-dispatch bounds (_dispatch_bounds) are the live rung."""
    tree_chunk = _res_ladder.halved(tree_chunk)
    if tree_chunk is None or tree_chunk >= n_trees:
        return jax.vmap(one)(keys)
    pad = (-n_trees) % tree_chunk
    keys_p = jnp.concatenate([keys, keys[:pad]]).reshape(-1, tree_chunk, 2)
    out = lax.map(jax.vmap(one), keys_p)
    return jax.tree.map(lambda a: a.reshape(-1, *a.shape[2:])[:n_trees], out)


def _bootstrap_weights(w, key):
    """Multinomial bootstrap over rows with positive weight (sklearn RF draws
    n_train samples with replacement; here n_train = round(sum(w))). Inverse-CDF
    sampling keeps memory at O(N), not O(N^2) like gumbel-categorical."""
    n = w.shape[0]
    total = jnp.sum(w)
    cdf = jnp.cumsum(w) / jnp.maximum(total, 1.0)
    u = jax.random.uniform(key, (n,))
    # side='right': smallest idx with cdf[idx] > u — a draw of exactly 0.0
    # must not select a leading zero-weight (fold-excluded) row.
    idx = jnp.searchsorted(cdf, u, side="right")
    keep = jnp.arange(n) < jnp.round(total).astype(jnp.int32)
    return jnp.zeros_like(w).at[jnp.clip(idx, 0, n - 1)].add(
        jnp.where(keep, 1.0, 0.0)
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_trees", "bootstrap", "random_splits", "sqrt_features", "max_depth",
        "max_nodes", "tree_chunk",
    ),
)
def fit_forest(x, y, w, key, *, n_trees, bootstrap, random_splits,
               sqrt_features, max_depth=48, max_nodes=None, tree_chunk=None,
               tree_keys=None):
    """Fit an ensemble. x [N,F]; y [N] (bool/int); w [N] >= 0 sample weights
    (0 = row excluded). Returns Forest with [T, ...] leading axis.

    DecisionTree = n_trees=1, bootstrap=False, random_splits=False,
    sqrt_features=False. RandomForest = 100/True/False/True.
    ExtraTrees = 100/False/True/True. (reference experiment.py:96-98)

    ``tree_chunk`` bounds how many trees grow concurrently: trees ride an
    inner vmap of that width under a sequential ``lax.map`` over chunks.
    The per-level workspace is O(trees_in_flight x F x N); an unchunked
    100-tree x 10-fold ensemble fit overruns TPU device memory, so
    sweep-level callers pass a chunk (results are identical — per-tree PRNG
    keys don't depend on the chunking).

    ``tree_keys`` [n_trees, 2] replaces the internal ``split(key, n_trees)``
    (see fit_forest_hist).
    """
    n, f = x.shape
    if max_nodes is None:
        max_nodes = 2 * n
    max_features = max(1, int(f ** 0.5)) if sqrt_features else None

    y01 = y.astype(x.dtype)
    w = w.astype(x.dtype)

    # Per-feature value order, shared by every tree (weights never reorder
    # values; parked rows are handled by the per-level node key).
    order0 = jnp.argsort(x.T, axis=1, stable=True).astype(jnp.int32)
    xsorted = jnp.take_along_axis(x.T, order0, axis=1)

    keys = jax.random.split(key, n_trees) if tree_keys is None else tree_keys
    assert keys.shape[0] == n_trees, (keys.shape, n_trees)

    def one(k):
        kb, kg = jax.random.split(k)
        wt = _bootstrap_weights(w, kb) if bootstrap else w
        return _fit_one_tree(
            x, y01, wt, kg, order0, xsorted, random_splits=random_splits,
            max_features=max_features, max_depth=max_depth, max_nodes=max_nodes,
        )

    feature, threshold, left, right, value, n_nodes = _map_trees(
        one, keys, n_trees, tree_chunk
    )
    return Forest(feature, threshold, left, right, value, n_nodes,
                  jnp.int32(max_depth))


# Window width of the gather-free predict sweep (lane-dim friendly;
# env-overridable for the hardware tuning sweep like the hist knobs).
PREDICT_WINDOW = int(os.environ.get("F16_PREDICT_WINDOW", "128"))


@functools.partial(jax.jit, static_argnames=("impl",))
def predict_proba(forest, x, impl=None):
    """Mean of per-tree leaf class distributions (sklearn soft vote:
    ensemble predict_proba averages per-tree normalized leaf counts).

    Two traversal formulations (``impl`` overrides: "gather"/"windows"):

    - "gather" — classic per-level node-table lookups. The default on
      every backend: the one at-size device A/B on record (hw_probe
      predict_ab, N=2000: gather 1 ms vs windows 5 ms steady) has it
      winning on the TPU too — at these table sizes the serialized-gather
      penalty (~70 M elem/s, PROFILE.md) is smaller than the windows
      formulation's re-entry overhead.
    - "windows" — sweep fixed node-id windows [k*W, (k+1)*W): per window,
      one [S,F]@[F,W] one-hot feature-select matmul + comparison table,
      then an inner loop routes resident samples (re-entered while any
      sample can still descend inside the window — node ids are monotone
      parent->child for both growers, so a forward sweep visits every
      path). No per-sample gathers except the final leaf-value read; the
      MXU-riding fallback if bigger forests ever flip the A/B.
    """
    if impl is None:
        impl = os.environ.get("F16_PREDICT_IMPL", "gather")
    s = x.shape[0]
    depth = jnp.max(forest.max_depth)  # scalar even if forests were stacked

    def one_gather(feature, threshold, left, right, value):
        def step(_, node):
            f = feature[node]
            leaf = f < 0
            xv = jnp.take_along_axis(x, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
            nxt = jnp.where(xv <= threshold[node], left[node], right[node])
            return jnp.where(leaf, node, nxt)

        node = lax.fori_loop(0, depth + 1, step, jnp.zeros(s, jnp.int32))
        return node

    def one_windows(feature, threshold, left, right, value, n_nodes):
        m = feature.shape[0]
        bw = min(PREDICT_WINDOW, m)
        # Pad node tables to a window multiple: dynamic_slice CLAMPS an
        # out-of-range start, which would silently misalign the final
        # partial window (rel uses the unclamped lo). Padding is leaf-like
        # (-1 feature) so no sample can route through it.
        pad = (-m) % bw
        if pad:
            feature = jnp.concatenate(
                [feature, jnp.full((pad,), -1, feature.dtype)])
            threshold = jnp.concatenate(
                [threshold, jnp.zeros((pad,), threshold.dtype)])
            left = jnp.concatenate([left, jnp.full((pad,), -1, left.dtype)])
            right = jnp.concatenate(
                [right, jnp.full((pad,), -1, right.dtype)])
        n_feat = x.shape[1]
        iota = jnp.arange(bw, dtype=jnp.int32)

        def routing_state(node, lo, leafw):
            rel = node - lo
            in_w = (rel >= 0) & (rel < bw)
            oh = (rel[:, None] == iota[None, :]) & in_w[:, None]
            at_leaf = jnp.sum(oh & leafw[None, :], axis=1) > 0
            return oh, in_w & ~at_leaf

        def window(state):
            k, node = state
            lo = k * bw
            featw = lax.dynamic_slice(feature, (lo,), (bw,))
            thrw = lax.dynamic_slice(threshold, (lo,), (bw,))
            leftw = lax.dynamic_slice(left, (lo,), (bw,))
            rightw = lax.dynamic_slice(right, (lo,), (bw,))
            leafw = featw < 0
            fsel = jax.nn.one_hot(featw, n_feat, dtype=x.dtype)  # [W, F]
            # HIGHEST precision: default TPU matmul rounds through bf16,
            # and thresholds are exact midpoints of these same values —
            # the one boundary-sensitive comparison in the whole traversal.
            xsel = jnp.matmul(x, fsel.T,
                              precision=lax.Precision.HIGHEST)   # [S, W]
            nxtw = jnp.where(xsel <= thrw[None, :], leftw[None, :],
                             rightw[None, :])                    # [S, W]

            def route(inner):
                node, oh, movable = inner
                nxt = jnp.sum(jnp.where(oh, nxtw, 0), axis=1)
                node = jnp.where(movable, nxt, node).astype(jnp.int32)
                oh, movable = routing_state(node, lo, leafw)
                return node, oh, movable

            def route_cond(inner):
                return jnp.any(inner[2])

            oh0, movable0 = routing_state(node, lo, leafw)
            node, _, _ = lax.while_loop(route_cond, route,
                                        (node, oh0, movable0))
            return k + 1, node

        def cond(state):
            k, _ = state
            return k * bw < n_nodes

        _, node = lax.while_loop(
            cond, window, (jnp.int32(0), jnp.zeros(s, jnp.int32))
        )
        return node

    if impl == "gather":
        def one(feature, threshold, left, right, value, n_nodes):
            node = one_gather(feature, threshold, left, right, value)
            v = value[node]
            return v / jnp.maximum(v.sum(-1, keepdims=True), 1e-30)
    elif impl == "windows":
        def one(feature, threshold, left, right, value, n_nodes):
            node = one_windows(feature, threshold, left, right, value,
                               n_nodes)
            v = value[node]
            return v / jnp.maximum(v.sum(-1, keepdims=True), 1e-30)
    else:
        raise ValueError(f"unknown predict impl {impl!r}")

    n_nodes_per_tree = jnp.max(
        forest.n_nodes.reshape(forest.feature.shape[0], -1), axis=-1
    ).astype(jnp.int32)
    probs = jax.vmap(one)(forest.feature, forest.threshold, forest.left,
                          forest.right, forest.value, n_nodes_per_tree)
    return jnp.mean(probs, axis=0)


def predict(forest, x):
    """Binary predict: class 1 iff p1 > p0 (argmax tie -> class 0, like np.argmax)."""
    p = predict_proba(forest, x)
    return p[:, 1] > p[:, 0]


def predict_batch(forests, x):
    """Batched ``predict``: a Forest whose leaves carry ONE extra leading
    batch axis (the sweep's per-fold forests, ``[folds, T, ...]``)
    evaluated against a shared matrix — returns ``[batch, N]`` bool.

    The explicit batched entry point of the planner/executor rework
    (ISSUE 12): the sweep's score closures (parallel/sweep.py score_one /
    score_folds_one) consume it, and the plan programs vmap it again
    over the config axis — so the fold-axis predict batching is owned
    here, next to the traversal kernels, instead of re-derived at every
    call site. Composes under further vmap/shard_map like any jax
    function; per-row results are bit-identical to ``predict`` on the
    corresponding un-batched Forest."""
    return jax.vmap(lambda f: predict(f, x))(forests)


# Cost attribution (obs/costs.py): host-level dispatches of the grower and
# predict entry points emit ``cost`` events; calls from inside an enclosing
# jit trace (the sweep's fused programs) pass through untouched. The hist
# core's events additionally carry the analytic per-stage flop split
# (``stage_flops``) so ``report --attrib`` can split the fit wall into
# bin / hist_build / split_scan / partition sub-stages.


def _fit_hist_cost_fields(args, kwargs):
    n, f = args[0].shape
    return {"stage_flops": fit_stage_flops(
        n=n, n_feat=f, n_bins=kwargs["n_bins"], n_trees=kwargs["n_trees"],
        n_nodes=kwargs["max_nodes"], max_nodes=kwargs["max_nodes"],
        node_batch=kwargs["node_batch"])}


_fit_forest_hist_core = _costs.instrument(
    _fit_forest_hist_core, "trees.fit_forest_hist",
    static_argnames=("n_trees", "bootstrap", "random_splits",
                     "sqrt_features", "max_depth", "max_nodes",
                     "tree_chunk", "n_bins", "hist_impl", "node_batch",
                     "refine"),
    cost_fields=_fit_hist_cost_fields)
fit_forest = _costs.instrument(
    fit_forest, "trees.fit_forest",
    static_argnames=("n_trees", "bootstrap", "random_splits",
                     "sqrt_features", "max_depth", "max_nodes",
                     "tree_chunk"))
predict_proba = _costs.instrument(predict_proba, "trees.predict_proba",
                                  static_argnames=("impl",))
