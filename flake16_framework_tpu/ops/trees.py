"""Tree-ensemble fit/predict as pure jitted JAX — the replacement for sklearn's
Cython tree stack (SURVEY.md §2 table B rows 1-3; reference call sites
/root/reference/experiment.py:96-98,469,473).

Design (TPU-first, not a port):

- **Static shapes.** A tree is a fixed-capacity structure-of-arrays
  (``Forest``): ``max_nodes`` slots regardless of data. Growth is level-by-level
  for ``max_depth`` iterations of a ``fori_loop``; a node that cannot split
  simply never changes, so finished trees are a fixed point and no dynamic
  control flow is needed.
- **Exact gini best-splits without per-node loops.** Per feature, sample order
  by value is precomputed once; each level a single *stable* argsort by node id
  yields (node, value)-lexicographic order, so weighted class prefix sums +
  per-node base offsets give every candidate split's left/right counts in one
  cumsum. This is the sort-based exact split of GPU gradient-boosting systems,
  mapped to XLA ops (batched over the feature axis, vmapped over trees).
- **Integer-exact scoring.** Weighted counts are small integers, exact in f32;
  the gini proxy is reformulated as ``d_L^2/w_L + d_R^2/w_R`` with
  ``d = w0 - w1`` (equal to sklearn's proxy up to a per-node constant), which
  removes the large constant term and keeps comparisons well-conditioned
  without f64.
- **Masking, not dynamic shapes.** Fold membership, resampler validity, and
  bootstrap multiplicities all arrive as one per-sample weight vector; rows
  with zero weight are parked in a dummy segment and never influence splits,
  thresholds, or leaf values — the moral equivalent of sklearn fitting on a
  shorter array, under XLA's static-shape rules.

Replicated sklearn 1.0.2 semantics (defaults of the reference estimators):
gini, ``splitter=best``/``random``, unbounded depth (bounded here by a generous
``max_depth``), ``min_samples_split=2``, ``min_samples_leaf=1``,
``max_features=sqrt`` for the ensembles and all features for the single tree,
midpoint thresholds with the ``<=`` left rule, candidate features drawn in
random order skipping constant features, pure nodes never split.
"""

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

# sklearn's FEATURE_THRESHOLD: two values closer than this are "equal" for
# split-candidate purposes.
FEATURE_EPS = 1e-7


class Forest(NamedTuple):
    """Structure-of-arrays tree ensemble. Shapes: [T, M] (+ [T, M, 2] value).

    ``feature`` is -1 at leaves; ``value`` holds *weighted class counts* for
    every node ever populated (internal nodes too — Tree SHAP needs node cover
    weights), normalized only at predict time like sklearn's predict_proba.
    """

    feature: jax.Array
    threshold: jax.Array
    left: jax.Array
    right: jax.Array
    value: jax.Array
    n_nodes: jax.Array
    max_depth: jax.Array  # scalar i32: depth bound used at fit time; predict
    # derives its traversal length from this so fit/predict can't disagree.


def _exclusive_cumsum(x):
    return jnp.concatenate([jnp.zeros_like(x[:1]), jnp.cumsum(x)[:-1]])


def _proxy_score(lw, lwy, rw, rwy, valid):
    """Weighted-gini proxy, maximized over candidates. Equal to sklearn's
    proxy up to a per-node constant: with d = w0 - w1 per side,
    d_L^2/w_L + d_R^2/w_R (see module docstring on conditioning)."""
    d_l = lw - 2.0 * lwy
    d_r = rw - 2.0 * rwy
    score = (
        d_l * d_l / jnp.maximum(lw, 1.0) + d_r * d_r / jnp.maximum(rw, 1.0)
    )
    return jnp.where(valid, score, -jnp.inf)


def _select_features(nc, key, max_features):
    """sklearn splitter feature sampling: draw features in uniform-random order,
    skip constants, stop after ``max_features`` non-constant ones.

    nc: [M1, F] bool — feature non-constant within node.
    Returns sel [M1, F] bool. With fewer than max_features non-constant
    features, all of them are selected (sklearn exhausts the draw).
    """
    if max_features is None:
        return nc
    u = jax.random.uniform(key, nc.shape)
    r = jnp.where(nc, u, jnp.inf)
    kth = jnp.sort(r, axis=1)[:, max_features - 1 : max_features]
    return (r <= kth) & nc


def _best_exact_splits(sample_node, w, wy, order0, xsorted, tot_w, tot_wy,
                       max_nodes):
    """Exact best-split search over all features for all current nodes.

    Returns (score [F, M1], thr [F, M1], nonconstant [F, M1]) where M1 =
    max_nodes + 1 (last segment parks zero-weight samples).
    """
    m1 = max_nodes + 1
    n = sample_node.shape[0]

    node_of = sample_node[order0]  # [F, N]
    perm = jnp.argsort(node_of, axis=1, stable=True)
    sidx = jnp.take_along_axis(order0, perm, axis=1)
    s_node = jnp.take_along_axis(node_of, perm, axis=1)
    s_val = jnp.take_along_axis(xsorted, perm, axis=1)
    s_w = w[sidx]
    s_wy = wy[sidx]

    cw = jnp.cumsum(s_w, axis=1)
    cwy = jnp.cumsum(s_wy, axis=1)
    start_w = _exclusive_cumsum(tot_w)
    start_wy = _exclusive_cumsum(tot_wy)

    lw = cw - start_w[s_node]
    lwy = cwy - start_wy[s_node]
    rw = tot_w[s_node] - lw
    rwy = tot_wy[s_node] - lwy

    nxt_node = jnp.concatenate([s_node[:, 1:], jnp.full_like(s_node[:, :1], -1)],
                               axis=1)
    nxt_val = jnp.concatenate([s_val[:, 1:], s_val[:, :1]], axis=1)
    valid = (
        (s_node == nxt_node)
        & (s_node < max_nodes)
        & (nxt_val - s_val > FEATURE_EPS)
        & (lw > 0)
        & (rw > 0)
    )

    score = _proxy_score(lw, lwy, rw, rwy, valid)

    seg = jax.vmap(
        lambda s, ids: jax.ops.segment_max(s, ids, num_segments=m1,
                                           indices_are_sorted=True)
    )
    best = seg(score, s_node)  # [F, M1]

    at_best = valid & (score == jnp.take_along_axis(best, s_node, axis=1))
    pos = jnp.where(at_best, jnp.arange(n)[None, :], n)
    segmin = jax.vmap(
        lambda s, ids: jax.ops.segment_min(s, ids, num_segments=m1,
                                           indices_are_sorted=True)
    )
    best_pos = jnp.clip(segmin(pos, s_node), 0, n - 2)  # [F, M1]

    v_lo = jnp.take_along_axis(s_val, best_pos, axis=1)
    v_hi = jnp.take_along_axis(s_val, best_pos + 1, axis=1)
    thr = (v_lo + v_hi) / 2.0
    thr = jnp.where(thr == v_hi, v_lo, thr)  # sklearn midpoint rounding guard

    return best, thr, jnp.isfinite(best)


def _best_random_splits(sample_node, w, wy, x, tot_w, tot_wy, max_nodes, key):
    """ExtraTrees random-threshold splits: per (node, feature) threshold uniform
    in [node_min, node_max), best among candidate features by the same proxy.
    No sorting — only segment min/max/sum — which is why ExtraTrees is the
    TPU-friendliest of the three reference models (SURVEY.md §2 table B)."""
    m1 = max_nodes + 1
    pos_w = w > 0

    xt = x.T  # [F, N]
    seg_min = jax.vmap(
        lambda v: jax.ops.segment_min(jnp.where(pos_w, v, jnp.inf), sample_node,
                                      num_segments=m1)
    )
    seg_max = jax.vmap(
        lambda v: jax.ops.segment_max(jnp.where(pos_w, v, -jnp.inf), sample_node,
                                      num_segments=m1)
    )
    nmin = seg_min(xt)  # [F, M1]
    nmax = seg_max(xt)
    nc = nmax > nmin + FEATURE_EPS

    u = jax.random.uniform(key, nmin.shape, dtype=x.dtype)
    thr = nmin + u * (nmax - nmin)
    thr = jnp.where(thr >= nmax, nmin, thr)  # sklearn RandomSplitter guard

    t_s = thr[:, :][:, sample_node]  # [F, N] threshold of each sample's node
    left = xt <= t_s

    seg_sum = jax.vmap(
        lambda v: jax.ops.segment_sum(v, sample_node, num_segments=m1)
    )
    lw = seg_sum(jnp.where(left, w[None, :], 0.0))
    lwy = seg_sum(jnp.where(left, wy[None, :], 0.0))
    rw = tot_w[None, :] - lw
    rwy = tot_wy[None, :] - lwy

    valid = nc & (lw > 0) & (rw > 0)
    score = _proxy_score(lw, lwy, rw, rwy, valid)

    return score, thr, nc


def _fit_one_tree(x, y01, w, key, order0, xsorted, *, random_splits,
                  max_features, max_depth, max_nodes):
    """Grow one tree level-by-level. All shapes static; returns Forest fields."""
    n, _ = x.shape
    m = max_nodes
    dt = x.dtype

    feature = jnp.full((m,), -1, jnp.int32)
    threshold = jnp.zeros((m,), dt)
    left = jnp.full((m,), -1, jnp.int32)
    right = jnp.full((m,), -1, jnp.int32)
    value = jnp.zeros((m, 2), dt)
    n_nodes = jnp.int32(1)
    # Zero-weight rows live in the parked segment `m` and never resurface.
    sample_node = jnp.where(w > 0, 0, m).astype(jnp.int32)

    wy = w * y01

    def level(d, state):
        feature, threshold, left, right, value, n_nodes, sample_node = state
        kf, kt = jax.random.split(jax.random.fold_in(key, d))

        tot_w = jax.ops.segment_sum(w, sample_node, num_segments=m + 1)
        tot_wy = jax.ops.segment_sum(wy, sample_node, num_segments=m + 1)

        # Record cover/class counts the first time a node holds samples.
        counts = jnp.stack([tot_w - tot_wy, tot_wy], axis=-1)[:m]
        value = jnp.where(tot_w[:m, None] > 0, counts, value)

        impure = (tot_wy > 0) & (tot_w - tot_wy > 0)

        if random_splits:
            score, thr, nc = _best_random_splits(
                sample_node, w, wy, x, tot_w, tot_wy, m, kt
            )
        else:
            score, thr, nc = _best_exact_splits(
                sample_node, w, wy, order0, xsorted, tot_w, tot_wy, m
            )

        sel = _select_features(nc.T, kf, max_features)  # [M1, F]
        score = jnp.where(sel.T, score, -jnp.inf)
        best_f = jnp.argmax(score, axis=0).astype(jnp.int32)  # [M1]
        best_score = jnp.max(score, axis=0)
        thr_node = jnp.take_along_axis(thr, best_f[None, :], axis=0)[0]

        ids = jnp.arange(m + 1)
        can_split = jnp.isfinite(best_score) & impure & (ids < m)
        rank = _exclusive_cumsum(can_split.astype(jnp.int32))
        left_id = n_nodes + 2 * rank
        right_id = left_id + 1
        can_split = can_split & (right_id < m)  # capacity guard (never hit
        # when max_nodes >= 2 * n_live_samples, the default)

        cs = can_split[:m]
        feature = jnp.where(cs, best_f[:m], feature)
        threshold = jnp.where(cs, thr_node[:m].astype(dt), threshold)
        left = jnp.where(cs, left_id[:m].astype(jnp.int32), left)
        right = jnp.where(cs, right_id[:m].astype(jnp.int32), right)
        n_nodes = n_nodes + 2 * jnp.sum(can_split, dtype=jnp.int32)

        node_s = sample_node
        moving = can_split[node_s] & (w > 0)
        f_s = best_f[node_s]
        go_left = jnp.take_along_axis(x, f_s[:, None], axis=1)[:, 0] <= (
            thr_node[node_s]
        )
        child = jnp.where(go_left, left_id[node_s], right_id[node_s])
        sample_node = jnp.where(moving, child, node_s).astype(jnp.int32)

        return feature, threshold, left, right, value, n_nodes, sample_node

    state = (feature, threshold, left, right, value, n_nodes, sample_node)
    state = lax.fori_loop(0, max_depth, level, state)
    feature, threshold, left, right, value, n_nodes, sample_node = state

    # Children created on the final level have had no value-recording pass yet
    # (the loop records counts at the *start* of each level); one last
    # segment_sum fills them so every reachable leaf has a distribution.
    tot_w = jax.ops.segment_sum(w, sample_node, num_segments=m + 1)
    tot_wy = jax.ops.segment_sum(wy, sample_node, num_segments=m + 1)
    counts = jnp.stack([tot_w - tot_wy, tot_wy], axis=-1)[:m]
    value = jnp.where(tot_w[:m, None] > 0, counts, value)

    return feature, threshold, left, right, value, n_nodes


def _bootstrap_weights(w, key):
    """Multinomial bootstrap over rows with positive weight (sklearn RF draws
    n_train samples with replacement; here n_train = round(sum(w))). Inverse-CDF
    sampling keeps memory at O(N), not O(N^2) like gumbel-categorical."""
    n = w.shape[0]
    total = jnp.sum(w)
    cdf = jnp.cumsum(w) / jnp.maximum(total, 1.0)
    u = jax.random.uniform(key, (n,))
    # side='right': smallest idx with cdf[idx] > u — a draw of exactly 0.0
    # must not select a leading zero-weight (fold-excluded) row.
    idx = jnp.searchsorted(cdf, u, side="right")
    keep = jnp.arange(n) < jnp.round(total).astype(jnp.int32)
    return jnp.zeros_like(w).at[jnp.clip(idx, 0, n - 1)].add(
        jnp.where(keep, 1.0, 0.0)
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_trees", "bootstrap", "random_splits", "sqrt_features", "max_depth",
        "max_nodes", "tree_chunk",
    ),
)
def fit_forest(x, y, w, key, *, n_trees, bootstrap, random_splits,
               sqrt_features, max_depth=48, max_nodes=None, tree_chunk=None):
    """Fit an ensemble. x [N,F]; y [N] (bool/int); w [N] >= 0 sample weights
    (0 = row excluded). Returns Forest with [T, ...] leading axis.

    DecisionTree = n_trees=1, bootstrap=False, random_splits=False,
    sqrt_features=False. RandomForest = 100/True/False/True.
    ExtraTrees = 100/False/True/True. (reference experiment.py:96-98)

    ``tree_chunk`` bounds how many trees grow concurrently: trees ride an
    inner vmap of that width under a sequential ``lax.map`` over chunks.
    The per-level split-search workspace is O(trees_in_flight x F x
    max_nodes); an unchunked 100-tree x 10-fold ensemble fit overruns TPU
    device memory, so sweep-level callers pass a chunk (results are
    identical — per-tree PRNG keys don't depend on the chunking).
    """
    n, f = x.shape
    if max_nodes is None:
        max_nodes = 2 * n
    max_features = max(1, int(f ** 0.5)) if sqrt_features else None

    y01 = y.astype(x.dtype)
    w = w.astype(x.dtype)

    if random_splits:
        order0 = xsorted = None
    else:
        order0 = jnp.argsort(x.T, axis=1, stable=True).astype(jnp.int32)
        xsorted = jnp.take_along_axis(x.T, order0, axis=1)

    keys = jax.random.split(key, n_trees)

    def one(k):
        kb, kg = jax.random.split(k)
        wt = _bootstrap_weights(w, kb) if bootstrap else w
        return _fit_one_tree(
            x, y01, wt, kg, order0, xsorted, random_splits=random_splits,
            max_features=max_features, max_depth=max_depth, max_nodes=max_nodes,
        )

    if tree_chunk is None or tree_chunk >= n_trees:
        feature, threshold, left, right, value, n_nodes = jax.vmap(one)(keys)
    else:
        pad = (-n_trees) % tree_chunk
        keys_p = jnp.concatenate([keys, keys[:pad]]).reshape(
            -1, tree_chunk, 2
        )
        out = lax.map(jax.vmap(one), keys_p)
        feature, threshold, left, right, value, n_nodes = jax.tree.map(
            lambda a: a.reshape(-1, *a.shape[2:])[:n_trees], out
        )
    return Forest(feature, threshold, left, right, value, n_nodes,
                  jnp.int32(max_depth))


@jax.jit
def predict_proba(forest, x):
    """Mean of per-tree leaf class distributions (sklearn soft vote:
    ensemble predict_proba averages per-tree normalized leaf counts).
    Traversal length comes from the forest's own fit-time depth bound."""
    s = x.shape[0]
    depth = jnp.max(forest.max_depth)  # scalar even if forests were stacked

    def one(feature, threshold, left, right, value):
        def step(_, node):
            f = feature[node]
            leaf = f < 0
            xv = jnp.take_along_axis(x, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
            nxt = jnp.where(xv <= threshold[node], left[node], right[node])
            return jnp.where(leaf, node, nxt)

        node = lax.fori_loop(0, depth + 1, step, jnp.zeros(s, jnp.int32))
        v = value[node]
        return v / jnp.maximum(v.sum(-1, keepdims=True), 1e-30)

    probs = jax.vmap(one)(forest.feature, forest.threshold, forest.left,
                          forest.right, forest.value)
    return jnp.mean(probs, axis=0)


def predict(forest, x):
    """Binary predict: class 1 iff p1 > p0 (argmax tie -> class 0, like np.argmax)."""
    p = predict_proba(forest, x)
    return p[:, 1] > p[:, 0]
