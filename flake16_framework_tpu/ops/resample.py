"""The five balancing kernels + runtime dispatch.

Reference grid axis (/root/reference/experiment.py:87-94): {None, TomekLinks,
SMOTE, ENN, SMOTE-ENN, SMOTE-Tomek}, imbalanced-learn 0.9.0 defaults. Their
semantics (re-derived, not copied — imblearn is unavailable here):

- TomekLinks: sample i is in a Tomek link iff its 1-NN j has a different class
  and j's 1-NN is i. 'auto' removes only non-minority link members; 'all'
  (the variant used inside SMOTETomek) removes every link member.
- ENN (n_neighbors=3, kind_sel='all'): a target-class sample is kept iff all 3
  of its nearest neighbours share its class. 'auto' cleans the majority class,
  'all' (inside SMOTEENN) cleans both.
- SMOTE (k_neighbors=5, 'auto'): synthesize n_maj - n_min minority samples;
  each is base + U(0,1) * (neighbour - base) with the neighbour drawn uniformly
  from the base's 5-NN within the minority class.
- SMOTEENN / SMOTETomek: SMOTE then the cleaner with sampling_strategy='all'.

TPU-first shape discipline (SURVEY.md §7 step 5 "hard part"): resampled sets
have data-dependent sizes, so every kernel returns fixed-capacity arrays
(x [cap,F], y [cap], w [cap]) where w is a 0/1 validity weight consumed
directly by the tree fitters' weight masking — dynamic shapes never exist.
The balancing axis is a runtime int dispatched with lax.switch, so one
compiled sweep graph covers all six settings.

RNG note: imblearn draws from numpy RandomState(0); we use jax PRNG. Resampled
draws are not bit-identical, parity is at the F1 level (BASELINE.md criterion).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from flake16_framework_tpu.ops.knn import masked_knn, nearest_one

SMOTE_K = 5
ENN_K = 3


def _class_counts(y, w):
    pos = jnp.sum(jnp.where(y, w, 0.0))
    neg = jnp.sum(w) - pos
    return neg, pos


def _pad_cap(x, y, w, cap):
    n, f = x.shape
    pad = cap - n
    x_out = jnp.concatenate([x, jnp.zeros((pad, f), x.dtype)])
    y_out = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
    w_out = jnp.concatenate([w, jnp.zeros((pad,), w.dtype)])
    return x_out, y_out, w_out


def tomek_keep(x, y, w, *, strategy_all):
    """0/1 keep mask implementing TomekLinks under-sampling."""
    valid = w > 0
    nn1 = nearest_one(x, valid)
    mutual = nn1[nn1] == jnp.arange(x.shape[0])
    diff = y[nn1] != y
    link = valid & diff & mutual

    if not strategy_all:
        neg, pos = _class_counts(y, w)
        majority_is_pos = pos >= neg
        link = link & (y == majority_is_pos)

    return jnp.where(valid & ~link, w, 0.0)


def enn_keep(x, y, w, *, strategy_all):
    """0/1 keep mask implementing EditedNearestNeighbours(kind_sel='all')."""
    valid = w > 0
    idx, ok = masked_knn(x, valid, ENN_K)
    # Missing neighbours (tiny classes) count as agreeing, i.e. never remove.
    same = (y[idx] == y[:, None]) | ~ok
    all_same = jnp.all(same, axis=1)

    target = valid
    if not strategy_all:
        neg, pos = _class_counts(y, w)
        majority_is_pos = pos >= neg
        target = target & (y == majority_is_pos)

    remove = target & ~all_same
    return jnp.where(valid & ~remove, w, 0.0)


def smote(x, y, w, key, cap):
    """SMOTE oversampling into fixed capacity: rows [0,N) are the originals,
    rows [N,cap) are synthetic slots, the first n_maj-n_min of which are valid."""
    n, f = x.shape
    neg, pos = _class_counts(y, w)
    minority_is_pos = pos < neg
    is_min = (w > 0) & (y == minority_is_pos)
    n_min = jnp.sum(is_min.astype(jnp.int32))
    n_maj = jnp.sum((w > 0).astype(jnp.int32)) - n_min
    # No minority samples in this fold: imblearn would raise; the masked
    # equivalent is a no-op (synthesizing from majority rows would poison
    # the training set with mislabeled copies).
    n_synth = jnp.where(
        n_min > 0, jnp.clip(n_maj - n_min, 0, cap - n), 0
    )

    idx, ok = masked_knn(x, is_min, SMOTE_K)

    # Minority rows in original order (stable argsort moves them to the front).
    min_order = jnp.argsort(~is_min, stable=True).astype(jnp.int32)

    n_slots = cap - n
    ki, ks = jax.random.split(key)
    # imblearn draw: one randint over the flattened [n_min x k] neighbour table.
    pick = jax.random.randint(
        ki, (n_slots,), 0, jnp.maximum(n_min * SMOTE_K, 1)
    )
    base = min_order[pick // SMOTE_K]
    col = pick % SMOTE_K
    nbr = idx[base, col]
    nbr = jnp.where(ok[base, col], nbr, base)  # degenerate tiny-minority guard

    steps = jax.random.uniform(ks, (n_slots, 1), dtype=x.dtype)
    x_new = x[base] + steps * (x[nbr] - x[base])
    slot_ok = jnp.arange(n_slots) < n_synth

    x_out = jnp.concatenate([x, jnp.where(slot_ok[:, None], x_new, 0.0)])
    y_out = jnp.concatenate([y, jnp.full((n_slots,), minority_is_pos, y.dtype)])
    w_out = jnp.concatenate([w, slot_ok.astype(w.dtype)])
    return x_out, y_out, w_out


@functools.partial(jax.jit, static_argnames=("cap",))
def resample(x, y, w, bal_code, key, cap):
    """Dispatch on the balancing code (config.BALANCINGS). Returns
    (x [cap,F], y [cap], w [cap]); w folds validity into sample weight."""

    def none_():
        return _pad_cap(x, y, w, cap)

    def tomek_():
        return _pad_cap(x, y, tomek_keep(x, y, w, strategy_all=False), cap)

    def smote_():
        return smote(x, y, w, key, cap)

    def enn_():
        return _pad_cap(x, y, enn_keep(x, y, w, strategy_all=False), cap)

    def smote_enn_():
        xs, ys, ws = smote(x, y, w, key, cap)
        return xs, ys, enn_keep(xs, ys, ws, strategy_all=True)

    def smote_tomek_():
        xs, ys, ws = smote(x, y, w, key, cap)
        return xs, ys, tomek_keep(xs, ys, ws, strategy_all=True)

    return lax.switch(
        bal_code, (none_, tomek_, smote_, enn_, smote_enn_, smote_tomek_)
    )
