"""Path-dependent Tree SHAP as pure JAX (reference: shap.TreeExplainer's C
extension, feature_perturbation='tree_path_dependent', called at
/root/reference/experiment.py:517; SURVEY.md §2 table B).

Formulation (the GPUTreeShap work-item decomposition, PAPERS.md arxiv
2010.13972): the forest is flattened into one global work list of
(instance, root-leaf path) items. A path's duplicate features merge
multiplicatively into per-unique-feature (zero_fraction z, interval
(lo, hi] whose membership is the one_fraction o), so each item is a compact
row of u <= min(F, depth) slots; the Shapley permutation weights come from
one EXTEND polynomial pass over the slots and one batched UNWIND — O(cap^2)
per item where cap is the item's bin. The host driver bin-packs items by u
into power-of-two caps so short paths stop paying the F = 16 worst case,
and runs each bin as ONE batched unit program — a Pallas TPU kernel on
device, and a bit-identical XLA program as the fallback ladder rung (both
compute the same per-(path-block, sample-block) partials via
``_unit_block_math`` and share one final block sum). A single-bucket
traceable variant (``_graph_forest_shap``) serves jit contexts: the serve
AOT executables and the planner's fused shap arm.

Beyond the paper's path-dependent mode, the same compact path form powers
``forest_shap_interventional`` (feature_perturbation='interventional'
against a background set, closed-form p!q! weighting) and
``forest_shap_interactions`` (SHAP interaction values via per-pair UNWIND).

Output convention matches the reference exactly: ``shap_values(X)[0]`` —
contributions to the *class-0 probability* of the soft-vote ensemble, an
[S, F] array (experiment.py:517 takes element [0] of the per-class list).

Local accuracy (sum_f phi_f(x) = p0(x) - E[p0]) is the built-in invariant the
tests enforce, alongside a brute-force subset-enumeration oracle on tiny trees.
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from flake16_framework_tpu.obs import costs as _costs
from flake16_framework_tpu.ops.trees import slice_trees, trim_nodes
from flake16_framework_tpu.resilience import ladder as _ladder


def extract_paths(feature, threshold, left, right, value, max_depth):
    """Tree arrays [M] -> per-leaf padded root-path steps.

    Returns dict with [L, D] step arrays (L = M//2+1 leaf slots, D = max_depth):
      sf: split feature of the ancestor; sthr: its threshold; sratio:
      cover(child)/cover(ancestor) for the path's child; sleft: whether the
      path goes left; svalid: step exists. Plus leaf_p0 [L] (class-0 prob),
      leaf_ok [L], leaf_cover_frac [L] (cover/root cover).
    Steps are ordered leaf -> root; order is irrelevant to the symmetric
    EXTEND polynomial.
    """
    m = feature.shape[0]
    d_max = max_depth
    cover = value.sum(-1)

    idx = jnp.arange(m)
    parent_buf = jnp.full((m + 1,), -1, jnp.int32)
    parent = parent_buf.at[jnp.where(left >= 0, left, m)].set(
        jnp.where(left >= 0, idx, -1).astype(jnp.int32)
    )
    parent = parent.at[jnp.where(right >= 0, right, m)].set(
        jnp.where(right >= 0, idx, -1).astype(jnp.int32)
    )
    parent = parent[:m]

    is_leaf = (feature < 0) & (cover > 0)
    n_slots = m // 2 + 1
    leaf_ids = jnp.argsort(~is_leaf, stable=True)[:n_slots].astype(jnp.int32)
    leaf_ok = is_leaf[leaf_ids]

    def walk(leaf):
        def step(carry, _):
            node = carry
            p = parent[node]
            ok = p >= 0
            psafe = jnp.maximum(p, 0)
            rec = (
                jnp.where(ok, feature[psafe], 0).astype(jnp.int32),
                jnp.where(ok, threshold[psafe], 0.0),
                jnp.where(ok, cover[node] / jnp.maximum(cover[psafe], 1e-30),
                          1.0),
                ok & (left[psafe] == node),
                ok,
            )
            return jnp.where(ok, psafe, node), rec

        _, recs = lax.scan(step, leaf, None, length=d_max)
        return recs

    sf, sthr, sratio, sleft, svalid = jax.vmap(walk)(leaf_ids)

    v0 = value[leaf_ids, 0]
    tot = jnp.maximum(value[leaf_ids].sum(-1), 1e-30)
    root_cover = jnp.maximum(cover[0], 1e-30)

    return {
        "sf": sf, "sthr": sthr, "sratio": sratio, "sleft": sleft,
        "svalid": svalid, "leaf_p0": v0 / tot, "leaf_ok": leaf_ok,
        "leaf_cover_frac": cover[leaf_ids] / root_cover,
    }


def _merge_path_features(paths, x, n_features):
    """Per (leaf, feature): presence, merged zero fraction z (product of cover
    ratios), and per-sample one fraction o (AND of branch indicators).

    Returns present [L, F], z [L, F], o [L, S, F].
    """
    sf, sratio, sthr, sleft, svalid = (
        paths["sf"], paths["sratio"], paths["sthr"], paths["sleft"],
        paths["svalid"],
    )
    l, d = sf.shape
    onehot = (sf[:, :, None] == jnp.arange(n_features)[None, None, :]) & (
        svalid[:, :, None]
    )  # [L, D, F]
    present = onehot.any(axis=1)
    z = jnp.prod(jnp.where(onehot, sratio[:, :, None], 1.0), axis=1)

    def sample_o(xs):  # xs: [F] one sample
        goes_left = xs[sf] <= sthr  # [L, D]
        ind = jnp.where(sleft, goes_left, ~goes_left)
        sat = jnp.where(onehot, ind[:, :, None], True)
        return jnp.all(sat, axis=1)  # [L, F]

    o = jax.vmap(sample_o, in_axes=0, out_axes=1)(x)  # [L, S, F]
    return present, z, o.astype(z.dtype)


def _extend_all(present, z, o, n_features):
    """Run the EXTEND polynomial over all (up to F) unique path features.

    present/z/o: [..., F]. Returns (w [..., F+2], l [...]) — the permutation
    weight vector and final path length (dummy element included).
    """
    shape = present.shape[:-1]
    f2 = n_features + 2
    i = jnp.arange(f2)

    w0 = jnp.zeros((*shape, f2), z.dtype).at[..., 0].set(1.0)
    l0 = jnp.ones(shape, z.dtype)  # dummy element counts 1

    def ext(carry, f):
        w, l = carry
        zf = z[..., f][..., None]
        of = o[..., f][..., None]
        pf = present[..., f]
        ln = l[..., None]
        # Functional form of the in-place EXTEND recurrence: position i keeps
        # z*w[i]*(l-i)/(l+1) and gains o*w[i-1]*i/(l+1) from below.
        stay = zf * w * (ln - i) / (ln + 1.0)
        up = of * jnp.concatenate(
            [jnp.zeros_like(w[..., :1]), w[..., :-1]], axis=-1
        ) * i / (ln + 1.0)
        w = jnp.where(pf[..., None], stay + up, w)
        l = l + pf.astype(l.dtype)
        return (w, l), None

    (w, l), _ = lax.scan(ext, (w0, l0), jnp.arange(n_features))
    return w, l


def _unwound_sum(w, l, z, o):
    """Sum of the path weights after UNWINDing one feature with fractions
    (z, o) — the inner loop of Tree SHAP's leaf accumulation, vectorized over
    the weight axis being implicit (runs the sequential recurrence over F+1
    positions).

    w: [..., F+2]; l: [...] path length (count incl. dummy); z,o: [...].
    """
    f2 = w.shape[-1]

    def step(carry, j):
        # iterate positions j = l-2 .. 0: run j over the static range high to
        # low, masking positions >= l-1.
        total, nxt = carry
        lm1 = l - 1.0
        active = (j <= lm1 - 1.0) & (lm1 > 0)
        wj = jnp.take(w, j.astype(jnp.int32), axis=-1)
        # o != 0 branch
        tmp = nxt * l / ((j + 1.0) * jnp.where(o == 0, 1.0, o))
        total_o = total + tmp
        nxt_o = wj - tmp * z * (lm1 - j) / l
        # o == 0 branch
        total_z = total + wj * l / (z * (lm1 - j))
        tot_new = jnp.where(o == 0, total_z, total_o)
        nxt_new = jnp.where(o == 0, nxt, nxt_o)
        total = jnp.where(active, tot_new, total)
        nxt = jnp.where(active, nxt_new, nxt)
        return (total, nxt), None

    # nxt starts at w[l-1]
    li = (l - 1.0).astype(jnp.int32)[..., None]
    nxt0 = jnp.take_along_axis(w, li, axis=-1)[..., 0]
    total0 = jnp.zeros_like(nxt0)
    js = jnp.arange(f2 - 2, -1, -1).astype(w.dtype)
    (total, _), _ = lax.scan(step, (total0, nxt0), js)
    return total


@functools.partial(jax.jit, static_argnames=("n_features",))
def tree_shap_single(paths, x, n_features):
    """phi [S, F] for one tree's class-0 leaf values."""
    present, z, o = _merge_path_features(paths, x, n_features)
    # broadcast z/present over samples: [L, S, F]
    zs = jnp.broadcast_to(z[:, None, :], o.shape)
    ps = jnp.broadcast_to(present[:, None, :], o.shape)

    w, l = _extend_all(ps, zs, o, n_features)  # [L, S, F+2], [L, S]

    def per_feature(f):
        u = _unwound_sum(w, l, zs[..., f], o[..., f])  # [L, S]
        phi_f = (o[..., f] - zs[..., f]) * u
        return jnp.where(ps[..., f], phi_f, 0.0)

    phi = jax.vmap(per_feature)(jnp.arange(n_features))  # [F, L, S]
    leaf_scale = jnp.where(paths["leaf_ok"], paths["leaf_p0"], 0.0)
    phi = jnp.einsum("fls,l->sf", phi, leaf_scale)
    return phi


def forest_shap_class0(forest, x, *, sample_chunk=None, impl="auto",
                       tree_chunk=None, _trim=True):
    """Mean over trees of per-tree class-0 Tree SHAP — the ensemble
    soft-vote's probability decomposition (what shap_values(X)[0] returns for
    a sklearn forest).

    forest: trees.Forest with [T, ...] axes.

    ``impl``: "pallas" (the TPU kernel below), "xla" (the lax.map/vmap
    formulation above), or "auto" — pallas on TPU, xla elsewhere (the kernel
    runs anywhere via the Pallas interpreter, but interpret mode is only
    meant for tests). For "xla", trees run under lax.map so only one tree's
    O(L*S*F) workspace is live; chunk samples via ``sample_chunk`` if even
    that is too large.

    ``tree_chunk`` splits the forest into ceil(T/tree_chunk)-sized slices
    explained in SEPARATE device dispatches (per-tree phis are additive, so
    the weighted recombination is exact). This bounds single-dispatch
    duration — the TPU tunnel faults on multi-minute dispatches (PROFILE.md)
    — unlike ``sample_chunk``, which only bounds the live workspace *inside*
    one dispatch.

    Both impls dispatch through module-level jits keyed on static shapes, so
    repeated explains (the 2 reference configs, the bench's steady-state
    timing) reuse one compiled program instead of re-lowering per call.
    """
    # Trim node-slot padding before anything else: the per-(leaf, sample)
    # workspace scales with M//2+1 leaf SLOTS, and fit-time max_nodes is a
    # worst-case bound typically several times the grown size. One host
    # sync of max(n_nodes), rounded up to keep the jit cache small; ONLY at
    # the top level — per-chunk re-trims would give chunks different M
    # buckets and recompile the SHAP program per chunk.
    if _trim:
        # Degradation ladder (resilience/ladder.py): after an OOM /
        # envelope-overrun the halved bounds shrink the live workspace and
        # the single-dispatch duration. Top level only — the tree_chunk
        # recursion below passes already-halved bounds with _trim=False.
        # F16_SHAP_TREE_CHUNK is consulted LIVE (per explain, not once at
        # import) so a mid-process export — e.g. an operator reacting to a
        # tunnel fault — takes effect on the next call and still rides the
        # ladder's halving path below. It is also a registered f16tune
        # knob (perf/tuner.py KNOBSPACE, target "shap", results-neutral):
        # the autotuner's winners export through this same read, so the
        # searched value and the operator override share one precedence
        # (explicit env beats any recorded winner).
        if tree_chunk is None:
            env = os.environ.get("F16_SHAP_TREE_CHUNK", "").strip()
            try:
                # floor 1 (G106 validator bound); a malformed export must
                # degrade to the unchunked default, not kill the explain —
                # this read sits on the serve path.
                tree_chunk = max(1, int(env)) if env else None
            except ValueError:
                tree_chunk = None
        sample_chunk = _ladder.halved(sample_chunk)
        tree_chunk = _ladder.halved(tree_chunk)
        m = forest.feature.shape[-1]
        n_used = int(jax.device_get(jnp.max(forest.n_nodes)))
        m_trim = min(m, max(128, -(-n_used // 128) * 128))
        if m_trim < m:
            forest = trim_nodes(forest, m_trim)

    t_total = forest.feature.shape[0]
    if tree_chunk is not None and tree_chunk < t_total:
        acc = None
        for lo in range(0, t_total, tree_chunk):
            sub = slice_trees(forest, lo, lo + tree_chunk)
            c = sub.feature.shape[0]
            phi = forest_shap_class0(sub, x, sample_chunk=sample_chunk,
                                     impl=impl, _trim=False) * c
            # Deliberate per-chunk block: tree_chunk exists to BOUND single
            # dispatch duration (device-fault envelope), so chunks must not
            # pipeline into one long in-flight tail.
            phi.block_until_ready()  # f16lint: disable=J402
            acc = phi if acc is None else acc + phi
        return acc / t_total
    auto = impl == "auto"
    if auto:
        impl = ("pallas" if jax.default_backend() == "tpu"
                and not _PALLAS_AUTO_BROKEN[0] else "xla")
    depth = int(forest.max_depth)  # static by construction (fit-time bound)
    if impl == "pallas":
        interpret = jax.default_backend() != "tpu"
        try:
            # block INSIDE the try: jit dispatch is async, so a device
            # fault would otherwise surface at the caller's sync, outside
            # this handler
            return jax.block_until_ready(_pallas_forest_shap(
                forest, x, depth=depth, interpret=interpret))
        except Exception as e:  # Mosaic lowering/runtime errors share no base
            # auto mode must never cost the SHAP stage a whole bench run
            # on the kernel's first-ever device attempt: fall back to the
            # XLA formulation (same values — interpret-mode equality is
            # test-pinned), remember the failure so chunked calls do not
            # re-attempt the broken compile per chunk, and say so.
            # Explicit impl="pallas" still raises — shap_equiv needs the
            # real error.
            if not auto:
                raise
            import sys

            # The pallas->xla rung of the degradation ladder: classifies
            # the failure, emits the fault/degrade obs event, and sets the
            # sticky per-process flag (resilience/ladder.py).
            _ladder.mark_pallas_broken(e)
            print(f"treeshap: pallas kernel failed on "
                  f"{jax.default_backend()} ({type(e).__name__}: "
                  f"{str(e)[:200]}); auto-falling back to impl='xla'",
                  file=sys.stderr, flush=True)
            impl = "xla"
    if impl != "xla":
        raise ValueError(f"unknown Tree SHAP impl {impl!r}")
    # Same host-packed driver as the pallas rung, on the bit-identical XLA
    # unit program — so an auto-mode fallback reproduces impl="xla" exactly.
    return _packed_forest_shap(forest, x, depth=depth,
                               sample_chunk=sample_chunk)


class _PallasBrokenProxy:
    """Back-compat view of the old sticky ``_PALLAS_AUTO_BROKEN = [False]``
    flag, now owned by the degradation ladder (resilience/ladder.py
    ``pallas_broken``): after an auto-mode kernel failure, every later auto
    call (including the remaining chunks of a tree_chunk loop) goes straight
    to the XLA formulation instead of re-running the failed Mosaic compile
    per chunk. Reads/writes of ``_PALLAS_AUTO_BROKEN[0]`` (tests, external
    scripts) keep working and see/steer the ladder state."""

    def __getitem__(self, i):
        if i != 0:
            raise IndexError(i)
        return _ladder.state().pallas_broken

    def __setitem__(self, i, v):
        if i != 0:
            raise IndexError(i)
        _ladder.state().pallas_broken = bool(v)

    def __repr__(self):
        return f"[{_ladder.state().pallas_broken}]"


_PALLAS_AUTO_BROKEN = _PallasBrokenProxy()


# --------------------------------------------------------------------------
# Work-item engine (GPUTreeShap decomposition)
# --------------------------------------------------------------------------

# Finite interval sentinels: +/-inf would turn the kernel's masked one-hot
# selects into 0*inf = NaN on dead slots; every real f32 input is < 3.4e38.
_BIG = 3.4e38

# Env-overridable tile shapes for the hardware tuning session (read at
# import, like the tree-grower knobs — tools/hw_probe.py runs each combo in
# a fresh subprocess). Samples ride the 128-wide lane axis; paths are
# blocked _PBLK at a time along sublanes.
_SBLK = int(os.environ.get("F16_SHAP_SBLK", "128"))
_LBLK = int(os.environ.get("F16_SHAP_LBLK", "8"))  # legacy kernel tile knob
_PBLK = int(os.environ.get("F16_SHAP_PBLK", "8"))


def _compact_paths_core(forest, depth, n_features):
    """Flatten the forest into the global work list: one row per
    (tree, leaf-slot), each a compact per-unique-feature path description.

    Returns dict of [P, F] / [P] arrays, P = T * leaf_slots:
      fid   int32  feature id per slot, present slots first (argsort order);
                   slots >= u are dead
      z     f32    merged zero fraction (product of the feature's cover
                   ratios along the path)
      lo,hi f32    merged branch constraints as one interval:
                   one_fraction o = (x > lo) & (x <= hi)
      u     int32  unique-feature count — live slots are exactly [0, u)
      scale f32    leaf_p0 for real leaves (the per-item output weight;
                   callers divide the summed phi by T — dividing at the
                   end instead of per item saves one rounding per term)
      valid bool   real leaf (leaf_ok & u > 0 rows are worth running)
    """
    paths = jax.vmap(
        lambda fe, th, le, ri, va: extract_paths(fe, th, le, ri, va, depth)
    )(forest.feature, forest.threshold, forest.left, forest.right,
      forest.value)

    sf, sthr, sratio, sleft, svalid = (
        paths["sf"], paths["sthr"], paths["sratio"], paths["sleft"],
        paths["svalid"])
    onehot = (sf[..., None] == jnp.arange(n_features)[None, None, None, :]
              ) & svalid[..., None]                       # [T, L, D, F]
    present = onehot.any(axis=2)                          # [T, L, F]
    z = jnp.prod(jnp.where(onehot, sratio[..., None], 1.0), axis=2)
    # Left steps bound from above (x <= thr), right steps from below
    # (x > thr); conjunction over duplicates collapses to one interval.
    left_oh = onehot & sleft[..., None]
    right_oh = onehot & ~sleft[..., None]
    hi = jnp.min(jnp.where(left_oh, sthr[..., None], _BIG), axis=2)
    lo = jnp.max(jnp.where(right_oh, sthr[..., None], -_BIG), axis=2)

    u = present.sum(axis=-1).astype(jnp.int32)            # [T, L]
    order = jnp.argsort(~present, axis=-1, stable=True)   # present first
    gather = lambda a: jnp.take_along_axis(a, order, axis=-1)
    scale = jnp.where(paths["leaf_ok"], paths["leaf_p0"], 0.0)

    flat = lambda a: a.reshape((-1,) + a.shape[2:])
    return {
        "fid": flat(order).astype(jnp.int32), "z": flat(gather(z)),
        "lo": flat(gather(lo)), "hi": flat(gather(hi)), "u": flat(u),
        "scale": flat(scale), "valid": flat(paths["leaf_ok"] & (u > 0)),
    }


@functools.partial(jax.jit, static_argnames=("depth", "n_features"))
def _compact_paths(forest, *, depth, n_features):
    return _compact_paths_core(forest, depth, n_features)


def _unit_block_math(fidb, zb, lob, hib, ub, scaleb, xt):
    """Partial phi for one (path-block, sample-block): [n_feat_k, sblk].

    fidb/zb/lob/hib: [pblk, cap] f32; ub/scaleb: [pblk] f32;
    xt: [n_feat_k, sblk] f32 (features x samples, both padded).

    Pure jnp on VALUES (no refs, no dynamic indexing — one-hot row selects
    throughout, the Mosaic-safe idiom), called verbatim from BOTH the
    Pallas kernel body and the XLA unit program so the two ladder rungs
    stay bit-identical: every select/scatter dot has at most one nonzero
    term per output cell (exact in f32 at HIGHEST precision) and the
    EXTEND/UNWIND arithmetic is the same expression graph, so equality
    holds to the last ulp, not just to tolerance.
    """
    f32 = jnp.float32
    hi_prec = lax.Precision.HIGHEST
    pblk, cap = fidb.shape
    n_feat_k, sblk = xt.shape
    c2 = cap + 2
    iota_p = lax.broadcasted_iota(f32, (1, pblk), 1)
    iota_c = lax.broadcasted_iota(f32, (cap, 1), 0)
    iota_f = lax.broadcasted_iota(f32, (cap, n_feat_k), 1)
    iota_i = lax.broadcasted_iota(f32, (c2, 1), 0)

    def one_path(p, acc):
        onehot_p = (iota_p == p.astype(f32)).astype(f32)   # [1, pblk]

        def sel(a):  # [pblk, cap] -> [cap, 1] row at p (masked sum, exact)
            return jnp.sum(a * onehot_p.T, axis=0)[:, None]

        fid_p, z_p = sel(fidb), sel(zb)
        lo_p, hi_p = sel(lob), sel(hib)
        u_p = jnp.sum(ub * onehot_p[0])
        sc_p = jnp.sum(scaleb * onehot_p[0])
        live = iota_c < u_p                                # [cap, 1]

        onehot_kf = ((fid_p == iota_f) & live).astype(f32)  # [cap, n_feat_k]
        x_sel = jnp.dot(onehot_kf, xt, preferred_element_type=f32,
                        precision=hi_prec)                 # [cap, sblk]
        o = ((x_sel > lo_p) & (x_sel <= hi_p)).astype(f32)

        # EXTEND over the cap slots (live slots are the prefix [0, u)).
        w0 = jnp.zeros((c2, sblk), f32).at[0, :].set(1.0)

        def ext(k, carry):
            w, l = carry
            onehot_k = (iota_c == k.astype(f32)).astype(f32)  # [cap, 1]
            pf = k.astype(f32) < u_p
            zf = jnp.sum(z_p * onehot_k)
            of = jnp.sum(o * onehot_k, axis=0)[None, :]       # [1, sblk]
            stay = zf * w * (l - iota_i) / (l + 1.0)
            w_shift = jnp.concatenate(
                [jnp.zeros((1, sblk), f32), w[:-1, :]], axis=0)
            up = of * w_shift * iota_i / (l + 1.0)
            return (jnp.where(pf, stay + up, w),
                    jnp.where(pf, l + 1.0, l))

        w, l = lax.fori_loop(0, cap, ext, (w0, jnp.float32(1.0)))

        # UNWIND every slot at once, positions high to low; total is the
        # sum of unwound weights, contrib_k = (o_k - z_k) * total * scale.
        onehot_li = (iota_i == (l - 1.0)).astype(f32)      # [c2, 1]
        w_l = jnp.sum(w * onehot_li, axis=0)               # [sblk]
        nxt0 = jnp.broadcast_to(w_l[None, :], (cap, sblk))
        z_sf = jnp.maximum(jnp.broadcast_to(z_p, (cap, sblk)), 1e-30)

        def unwind(jj, carry):
            total, nxt = carry
            j = jnp.float32(c2 - 2) - jj.astype(f32)
            activ = j <= l - 2.0
            onehot_j = (iota_i == j).astype(f32)           # [c2, 1]
            wj = jnp.broadcast_to(
                jnp.sum(w * onehot_j, axis=0)[None, :], (cap, sblk))
            o_safe = jnp.where(o == 0, 1.0, o)
            tmp = nxt * l / ((j + 1.0) * o_safe)
            total_o = total + tmp
            nxt_o = wj - tmp * z_sf * (l - 1.0 - j) / l
            total_z = total + wj * l / (z_sf * (l - 1.0 - j))
            tot_new = jnp.where(o == 0, total_z, total_o)
            nxt_new = jnp.where(o == 0, nxt, nxt_o)
            return (jnp.where(activ, tot_new, total),
                    jnp.where(activ, nxt_new, nxt))

        total, _ = lax.fori_loop(
            0, c2 - 1, unwind, (jnp.zeros((cap, sblk), f32), nxt0))

        contrib = jnp.where(live & (l > 1.0),
                            (o - z_p) * total * sc_p, 0.0)  # [cap, sblk]
        # Scatter slots -> features; each (f, s) cell has at most one
        # nonzero term (fids are unique on a path), so the dot is exact
        # and order-independent.
        return acc + jnp.dot(onehot_kf.T, contrib,
                             preferred_element_type=f32, precision=hi_prec)

    return lax.fori_loop(0, pblk, one_path,
                         jnp.zeros((n_feat_k, sblk), f32))


def _unit_kernel(fid_ref, z_ref, lo_ref, hi_ref, u_ref, scale_ref, xt_ref,
                 out_ref):
    out_ref[0] = _unit_block_math(
        fid_ref[:], z_ref[:], lo_ref[:], hi_ref[:],
        u_ref[0], scale_ref[0], xt_ref[:])


def _unit_partials(fid, z, lo, hi, u, scale, x, *, use_pallas,
                   interpret=False):
    """Per-(path-block) partial phis [n_pb, n_feat_k, s_tot], traceable.

    No cross-block accumulation happens here — the caller owns the single
    final block sum, so the pallas and XLA variants (which emit identical
    partials) reduce in the same order and agree bitwise.
    """
    r, cap = fid.shape
    s, n_features = x.shape
    n_feat_k = max(8, n_features + (-n_features) % 8)
    s_tot = s + (-s) % _SBLK
    xt = jnp.pad(x.T.astype(jnp.float32),
                 ((0, n_feat_k - n_features), (0, s_tot - s)))
    n_pb = r // _PBLK
    f32 = jnp.float32
    fid_f = fid.astype(f32)
    z_f, lo_f, hi_f = z.astype(f32), lo.astype(f32), hi.astype(f32)
    u_f = u.astype(f32).reshape(n_pb, _PBLK)
    sc_f = scale.astype(f32).reshape(n_pb, _PBLK)
    if use_pallas:
        row_spec = pl.BlockSpec((_PBLK, cap), lambda pb, sb: (pb, 0))
        meta_spec = pl.BlockSpec((1, _PBLK), lambda pb, sb: (pb, 0))
        return pl.pallas_call(
            _unit_kernel,
            grid=(n_pb, s_tot // _SBLK),
            in_specs=[row_spec, row_spec, row_spec, row_spec,
                      meta_spec, meta_spec,
                      pl.BlockSpec((n_feat_k, _SBLK),
                                   lambda pb, sb: (0, sb))],
            out_specs=pl.BlockSpec((1, n_feat_k, _SBLK),
                                   lambda pb, sb: (pb, 0, sb)),
            out_shape=jax.ShapeDtypeStruct((n_pb, n_feat_k, s_tot), f32),
            interpret=interpret,
        )(fid_f, z_f, lo_f, hi_f, u_f, sc_f, xt)

    blk = lambda a: a.reshape(n_pb, _PBLK, cap)
    xtb = xt.reshape(n_feat_k, s_tot // _SBLK, _SBLK)

    def one_block(fb, zb, lb, hb, ub, sb):
        per_tile = jax.vmap(
            lambda xt_blk: _unit_block_math(fb, zb, lb, hb, ub, sb, xt_blk),
            in_axes=1, out_axes=1)(xtb)    # [n_feat_k, st, _SBLK]
        return per_tile.reshape(n_feat_k, s_tot)

    return jax.vmap(one_block)(blk(fid_f), blk(z_f), blk(lo_f), blk(hi_f),
                               u_f, sc_f)


@jax.jit
def _unit_shap_xla(fid, z, lo, hi, u, scale, x):
    return _unit_partials(fid, z, lo, hi, u, scale, x, use_pallas=False)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _unit_shap_pallas(fid, z, lo, hi, u, scale, x, *, interpret=False):
    return _unit_partials(fid, z, lo, hi, u, scale, x, use_pallas=True,
                          interpret=interpret)


def _pack_work_items(comp, *, n_features, depth):
    """Host-side bin packing: work items -> [(cap, row_ids), ...].

    Each kept row (real leaf, u > 0) lands in the bucket whose cap is the
    next power of two >= its unique-feature count u, clamped to
    min(F, depth) — so the number of occupied buckets (and hence unit
    dispatches) is <= log2(F) + 1 = O(1).
    """
    u = np.asarray(comp["u"])
    keep = np.asarray(comp["valid"]) & (u > 0)
    cap_max = int(min(n_features, depth))
    caps = np.minimum(
        np.power(2, np.ceil(np.log2(np.maximum(u, 1)))).astype(np.int64),
        cap_max)
    return [(int(cap), np.nonzero(keep & (caps == cap))[0])
            for cap in sorted(set(caps[keep].tolist()))]


def packing_histogram(forest, n_features, *, depth=None):
    """Bin-packing census for one forest: {cap: {paths, mean_u,
    slot_util}} — the PROFILE.md packing histogram and the knob-tuning
    signal (slot_util near 1.0 means the caps fit the path population)."""
    depth = int(forest.max_depth) if depth is None else depth
    comp = jax.device_get(
        _compact_paths(forest, depth=depth, n_features=n_features))
    u = np.asarray(comp["u"])
    return {
        cap: {
            "paths": int(rows.size),
            "mean_u": float(u[rows].mean()),
            "slot_util": float(u[rows].mean() / cap),
        }
        for cap, rows in _pack_work_items(comp, n_features=n_features,
                                          depth=depth)
    }


def _packed_forest_shap(forest, x, *, depth, use_pallas=False,
                        interpret=False, sample_chunk=None):
    """Host-packed explain: compact the forest once, bin-pack the work
    list, run one unit program per occupied cap bucket, and sum the
    per-block partials. Identical values (bitwise) for the XLA and pallas
    units — see _unit_block_math."""
    s, n_features = x.shape
    if sample_chunk is not None and sample_chunk < s:
        outs = [
            _packed_forest_shap(forest, x[lo_:lo_ + sample_chunk],
                                depth=depth, use_pallas=use_pallas,
                                interpret=interpret)
            for lo_ in range(0, s, sample_chunk)]
        return jnp.concatenate(outs, axis=0)
    comp = jax.device_get(
        _compact_paths(forest, depth=depth, n_features=n_features))
    plan = _pack_work_items(comp, n_features=n_features, depth=depth)
    phi = jnp.zeros((s, n_features), jnp.float32)
    unit = (functools.partial(_unit_shap_pallas, interpret=interpret)
            if use_pallas else _unit_shap_xla)
    for cap, rows in plan:
        # Rows pad to the next power of two (>= _PBLK) so repeated explains
        # of similarly-sized forests reuse one compiled unit per (cap, pow2)
        # instead of recompiling per exact row count.
        r_pad = max(_PBLK, 1 << max(0, int(rows.size) - 1).bit_length())
        r_pad += (-r_pad) % _PBLK

        def take(name):
            a = comp[name][rows]
            a = a[:, :cap] if a.ndim == 2 else a
            return np.pad(a, [(0, r_pad - rows.size)] + [(0, 0)] *
                          (a.ndim - 1))

        parts = unit(take("fid"), take("z"), take("lo"), take("hi"),
                     take("u"), take("scale"), x)
        phi = phi + jnp.sum(parts, axis=0)[:n_features, :s].T
    return phi / forest.feature.shape[0]


def _graph_forest_shap(forest, x, *, depth, use_pallas=False,
                       interpret=False):
    """Traceable single-bucket engine (cap = min(F, depth)): keeps every
    (tree, leaf-slot) row masked instead of host-packed, so the whole
    explain stays inside one jitted program — what the serve AOT
    executables and the planner's fused shap arm compile."""
    s, n_features = x.shape
    comp = _compact_paths_core(forest, depth, n_features)
    cap = int(min(n_features, depth))
    p = comp["fid"].shape[0]
    r_pad = -(-p // _PBLK) * _PBLK

    def pad(a):
        return jnp.pad(a, [(0, r_pad - p)] + [(0, 0)] * (a.ndim - 1))

    scale = jnp.where(comp["valid"], comp["scale"], 0.0)
    u = jnp.where(comp["valid"], comp["u"], 0)
    parts = _unit_partials(
        pad(comp["fid"][:, :cap]), pad(comp["z"][:, :cap]),
        pad(comp["lo"][:, :cap]), pad(comp["hi"][:, :cap]),
        pad(u), pad(scale), x, use_pallas=use_pallas, interpret=interpret)
    return jnp.sum(parts, axis=0)[:n_features, :s].T / forest.feature.shape[0]


@functools.partial(jax.jit, static_argnames=("depth", "sample_chunk"))
def _xla_forest_shap(forest, x, *, depth, sample_chunk=None):
    """In-graph explain program (the serve "shap_xla" executable and the
    audit's traced SHAP entry). ``sample_chunk`` bounds the live
    workspace inside the one dispatch via lax.map over sample tiles."""
    n_features = x.shape[1]
    if sample_chunk is None:
        return _graph_forest_shap(forest, x, depth=depth)
    n = x.shape[0]
    pads = (-n) % sample_chunk
    xp = jnp.pad(x, ((0, pads), (0, 0)))
    chunks = xp.reshape(-1, sample_chunk, n_features)
    out = lax.map(
        lambda c: _graph_forest_shap(forest, c, depth=depth), chunks)
    return out.reshape(-1, n_features)[:n]


@functools.partial(jax.jit, static_argnames=("depth", "interpret"))
def _pallas_graph_shap(forest, x, *, depth, interpret=False):
    """In-graph explain on the Pallas unit kernel — the serve
    "shap_pallas" executable (single bucket; TPU only off-interpret)."""
    return _graph_forest_shap(forest, x, depth=depth, use_pallas=True,
                              interpret=interpret)


def _pallas_forest_shap(forest, x, *, depth, interpret):
    """Host-packed explain on the Pallas unit kernel — the auto/TPU rung
    of forest_shap_class0 (one unit dispatch per occupied cap bucket)."""
    return _packed_forest_shap(forest, x, depth=depth, use_pallas=True,
                               interpret=interpret)


# Cost attribution (obs/costs.py): the compiled explain programs. The
# packed driver dispatches units from host (concrete arrays, AOT-able);
# the graph programs are what serve and the fused plan arm compile.
_xla_forest_shap = _costs.instrument(
    _xla_forest_shap, "shap.xla_forest",
    static_argnames=("depth", "sample_chunk"))
_pallas_graph_shap = _costs.instrument(
    _pallas_graph_shap, "shap.pallas_graph",
    static_argnames=("depth", "interpret"))
_unit_shap_xla = _costs.instrument(_unit_shap_xla, "shap.unit_xla")
_unit_shap_pallas = _costs.instrument(
    _unit_shap_pallas, "shap.unit_pallas", static_argnames=("interpret",))
_compact_paths = _costs.instrument(
    _compact_paths, "shap.compact", static_argnames=("depth", "n_features"))


def expected_p0(forest):
    """Base value E[p0] under path-dependent cover weighting, per tree then
    averaged — pairs with forest_shap_class0 for the local-accuracy check."""
    def one(args):
        fe, th, le, ri, va = args
        paths = extract_paths(fe, th, le, ri, va, int(forest.max_depth))
        return jnp.sum(
            jnp.where(paths["leaf_ok"],
                      paths["leaf_p0"] * paths["leaf_cover_frac"], 0.0)
        )

    vals = lax.map(
        one,
        (forest.feature, forest.threshold, forest.left, forest.right,
         forest.value),
    )
    return jnp.mean(vals)


# --------------------------------------------------------------------------
# Interventional SHAP (feature_perturbation='interventional')
# --------------------------------------------------------------------------
#
# Closed form over the compact path rows: for a (leaf, x, b) triple the
# path's unique features partition into both-satisfied (irrelevant — the
# Shapley sum telescopes them away), neither-satisfied (leaf unreachable
# for every coalition -> 0), x-only (count p) and b-only (count q); then
#   phi_i += leaf_w * (p-1)! q! / (p+q)!   for i in x-only
#   phi_i -= leaf_w * p! (q-1)! / (p+q)!   for i in b-only
# averaged over the background rows. Everything reduces to three
# (slots x slots) contractions per row chunk — pure matmuls.


def _interventional_tables(n_features):
    """f64-exact (p, q) weight tables, built at trace time (static F)."""
    f = [math.factorial(i) for i in range(n_features + 1)]
    wx = np.zeros((n_features + 1, n_features + 1))
    wb = np.zeros((n_features + 1, n_features + 1))
    for pp in range(n_features + 1):
        for qq in range(n_features + 1 - pp):
            if pp >= 1:
                wx[pp, qq] = f[pp - 1] * f[qq] / f[pp + qq]
            if qq >= 1:
                wb[pp, qq] = f[pp] * f[qq - 1] / f[pp + qq]
    return jnp.asarray(wx, jnp.float32), jnp.asarray(wb, jnp.float32)


@functools.partial(jax.jit, static_argnames=("depth", "row_chunk"))
def _interventional_jit(forest, x, background, *, depth, row_chunk):
    s, n_features = x.shape
    b = background.shape[0]
    f32 = jnp.float32
    comp = _compact_paths_core(forest, depth, n_features)
    scale = jnp.where(comp["valid"], comp["scale"], 0.0)
    p_rows = comp["fid"].shape[0]
    rc = p_rows if row_chunk is None else min(row_chunk, p_rows)
    r_pad = (-p_rows) % rc

    def pad(a):
        return jnp.pad(a, [(0, r_pad)] + [(0, 0)] * (a.ndim - 1))

    def chunks(a):
        return pad(a).reshape((-1, rc) + a.shape[1:])

    wx_t, wb_t = _interventional_tables(n_features)
    k = n_features  # slot count (compact rows keep all F slots here)

    def one_chunk(args):
        fidc, loc, hic, uc, scc = args
        live = (jnp.arange(k)[None, :] < uc[:, None]).astype(f32)  # [R, K]

        def sat(pts):  # [N, F] -> [R, N, K] interval membership, live only
            g = jnp.take(pts, fidc, axis=1)        # [N, R, K]
            o = (g > loc[None]) & (g <= hic[None])
            return jnp.moveaxis(o, 0, 1).astype(f32) * live[:, None, :]

        ox = sat(x)                                # [R, S, K]
        ob = sat(background)                       # [R, B, K]
        nx = live[:, None, :] - ox                 # live but x-unsatisfied
        nb = live[:, None, :] - ob
        pcnt = jnp.einsum("rsk,rbk->rsb", ox, nb)  # x-only counts
        qcnt = jnp.einsum("rsk,rbk->rsb", nx, ob)  # b-only counts
        ncnt = jnp.einsum("rsk,rbk->rsb", nx, nb)  # neither -> unreachable
        ok = (ncnt < 0.5).astype(f32) * scc[:, None, None]
        idx = (pcnt.astype(jnp.int32) * (n_features + 1)
               + qcnt.astype(jnp.int32))
        a_w = jnp.take(wx_t.reshape(-1), idx) * ok   # [R, S, B]
        b_w = jnp.take(wb_t.reshape(-1), idx) * ok
        tx = jnp.einsum("rbk,rsb->rsk", nb, a_w)
        tb = jnp.einsum("rbk,rsb->rsk", ob, b_w)
        phi_slots = ox * tx - nx * tb               # [R, S, K]
        onehot = ((fidc[..., None] == jnp.arange(n_features))
                  & (live[..., None] > 0)).astype(f32)  # [R, K, F]
        return jnp.einsum("rsk,rkf->sf", phi_slots, onehot)

    per = lax.map(one_chunk, (chunks(comp["fid"]), chunks(comp["lo"]),
                              chunks(comp["hi"]), chunks(comp["u"]),
                              chunks(scale)))
    return jnp.sum(per, axis=0) / (b * forest.feature.shape[0])


def forest_shap_interventional(forest, x, background, *, row_chunk=64):
    """Interventional SHAP of the class-0 soft-vote probability vs a
    background set: phi [S, F] with sum_f phi[s] = p0(x_s) - mean_b p0(b).
    ``row_chunk`` bounds the [rows, S, B] workspace per lax.map step."""
    return _interventional_jit(forest, x, background,
                               depth=int(forest.max_depth),
                               row_chunk=row_chunk)


# --------------------------------------------------------------------------
# SHAP interaction values
# --------------------------------------------------------------------------


def _unwind_weights(w, l, z, o):
    """Full UNWIND: the permutation-weight vector with one feature
    (fractions z, o) removed — positions [0, l-2) valid, i.e. a path of
    length l-1. w: [..., F2]; l, z, o broadcastable to w[..., 0].
    ``_unwound_sum(w, l, z, o)`` equals ``_unwind_weights(...)`` summed
    over its valid positions; the full vector is what the interaction
    recurrence needs (a second UNWIND runs on it for the partner
    feature)."""
    f2 = w.shape[-1]
    iota = jnp.arange(f2)
    li = (l - 1.0).astype(jnp.int32)[..., None]
    n0 = jnp.take_along_axis(w, jnp.clip(li, 0, f2 - 1), axis=-1)[..., 0]
    m0 = jnp.zeros_like(w)

    def step(carry, j):
        n, m = carry
        lm1 = l - 1.0
        active = (j <= lm1 - 1.0) & (lm1 > 0)
        wj = jnp.take(w, j.astype(jnp.int32), axis=-1)
        o_safe = jnp.where(o == 0, 1.0, o)
        mj_o = n * l / ((j + 1.0) * o_safe)
        n_new = wj - mj_o * z * (lm1 - j) / l
        mj_z = wj * l / (jnp.maximum(z, 1e-30) * (lm1 - j))
        mj = jnp.where(o == 0, mj_z, mj_o)
        onehot_j = jnp.arange(f2) == j.astype(jnp.int32)
        m = jnp.where(active[..., None] & onehot_j, mj[..., None], m)
        n = jnp.where(active & (o != 0), n_new, n)
        return (n, m), None

    js = jnp.arange(f2 - 2, -1, -1).astype(w.dtype)
    (_, m), _ = lax.scan(step, (n0, m0), js)
    return m


@functools.partial(jax.jit, static_argnames=("depth", "row_chunk"))
def _interactions_jit(forest, x, *, depth, row_chunk):
    s, n_features = x.shape
    f32 = jnp.float32
    comp = _compact_paths_core(forest, depth, n_features)
    scale = jnp.where(comp["valid"], comp["scale"], 0.0)
    cap = int(min(n_features, depth))
    p_rows = comp["fid"].shape[0]
    rc = p_rows if row_chunk is None else min(row_chunk, p_rows)
    r_pad = (-p_rows) % rc

    def chunks(a):
        a = a[:, :cap] if a.ndim == 2 else a
        a = jnp.pad(a, [(0, r_pad)] + [(0, 0)] * (a.ndim - 1))
        return a.reshape((-1, rc) + a.shape[1:])

    def one_chunk(args):
        fidc, zc, loc, hic, uc, scc = args          # [R, K] / [R]
        live = jnp.arange(cap)[None, :] < uc[:, None]
        g = jnp.take(x, fidc, axis=1)               # [S, R, K]
        o = jnp.moveaxis(
            ((g > loc[None]) & (g <= hic[None])), 0, 1
        ).astype(f32) * live[:, None, :].astype(f32)  # [R, S, K]
        pres = jnp.broadcast_to(live[:, None, :], o.shape)
        zb = jnp.broadcast_to(zc[:, None, :], o.shape)
        w, l = _extend_all(pres, zb, o, cap)        # [R, S, K+2], [R, S]

        def slot(a, i):
            return jnp.take(a, i, axis=-1)          # [R, S]

        totals = jax.vmap(
            lambda i: _unwound_sum(w, l, slot(zb, i), slot(o, i))
        )(jnp.arange(cap))                          # [K, R, S]
        phi_slots = jnp.where(
            live[:, None, :],
            (o - zb) * jnp.moveaxis(totals, 0, -1) * scc[:, None, None],
            0.0)                                    # [R, S, K]
        onehot = ((fidc[..., None] == jnp.arange(n_features))
                  & live[..., None]).astype(f32)    # [R, K, F]
        phi_sf = jnp.einsum("rsk,rkf->sf", phi_slots, onehot)

        mj = jax.vmap(
            lambda j: _unwind_weights(w, l, slot(zb, j), slot(o, j))
        )(jnp.arange(cap))                          # [K, R, S, K+2]

        def pair(j, i):
            # phi_ij contribution of this path: condition feature j
            # present vs absent, then the usual unwound sum for i on the
            # j-removed weight vector (length l-1).
            tot = _unwound_sum(jnp.take(mj, j, axis=0), l - 1.0,
                               slot(zb, i), slot(o, i))  # [R, S]
            val = (0.5 * (slot(o, j) - slot(zb, j))
                   * (slot(o, i) - slot(zb, i)) * tot * scc[:, None])
            mask = (jnp.take(live, j, axis=-1)
                    & jnp.take(live, i, axis=-1))[:, None] & (i != j)
            return jnp.where(mask, val, 0.0)

        pv = jax.vmap(lambda j: jax.vmap(lambda i: pair(j, i))(
            jnp.arange(cap)))(jnp.arange(cap))      # [K, K, R, S]
        off = jnp.einsum("jirs,rjf,rig->sfg", pv, onehot, onehot)
        return phi_sf, off

    per_phi, per_off = lax.map(
        one_chunk,
        (chunks(comp["fid"]), chunks(comp["z"]), chunks(comp["lo"]),
         chunks(comp["hi"]), chunks(comp["u"]), chunks(scale)))
    t = forest.feature.shape[0]
    phi = jnp.sum(per_phi, axis=0) / t              # [S, F]
    off = jnp.sum(per_off, axis=0) / t              # [S, F, F]
    off = 0.5 * (off + jnp.swapaxes(off, 1, 2))     # symmetry exact
    # Diagonal completes each row to the path-dependent phi, so row sums
    # (and hence the full-matrix sum) keep local accuracy by construction.
    diag = phi - jnp.sum(off, axis=2)
    return off + diag[..., None] * jnp.eye(n_features, dtype=off.dtype)


def forest_shap_interactions(forest, x, *, row_chunk=32):
    """SHAP interaction values of the class-0 soft-vote probability:
    [S, F, F] with phi_ij == phi_ji and row sums equal to the
    path-dependent phi (so the matrix sums to p0(x) - E[p0])."""
    return _interactions_jit(forest, x, depth=int(forest.max_depth),
                             row_chunk=row_chunk)


_interventional_jit = _costs.instrument(
    _interventional_jit, "shap.interventional",
    static_argnames=("depth", "row_chunk"))
_interactions_jit = _costs.instrument(
    _interactions_jit, "shap.interactions",
    static_argnames=("depth", "row_chunk"))
