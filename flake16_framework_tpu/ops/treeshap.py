"""Path-dependent Tree SHAP as pure JAX (reference: shap.TreeExplainer's C
extension, feature_perturbation='tree_path_dependent', called at
/root/reference/experiment.py:517; SURVEY.md §2 table B).

Formulation: instead of the reference's sequential recursive EXTEND/UNWIND
walk, we use the leaf-parallel decomposition (the GPUTreeShap insight — see
PAPERS.md): each (leaf, sample) pair contributes independently. For a leaf's
root path, duplicate features merge multiplicatively into per-feature
(zero_fraction z_f, one_fraction o_f) with at most F unique entries; the
Shapley permutation weights come from one EXTEND polynomial pass over the F
feature slots and one UNWIND per present feature — O(F^2) per (leaf, sample),
F = 16. Leaves and samples ride vmap axes; trees are summed with lax.map so
only one tree's workspace is live at a time. This maps to the TPU VPU as large
elementwise/scan batches instead of pointer-chasing recursion.

Output convention matches the reference exactly: ``shap_values(X)[0]`` —
contributions to the *class-0 probability* of the soft-vote ensemble, an
[S, F] array (experiment.py:517 takes element [0] of the per-class list).

Local accuracy (sum_f phi_f(x) = p0(x) - E[p0]) is the built-in invariant the
tests enforce, alongside a brute-force subset-enumeration oracle on tiny trees.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax


def extract_paths(feature, threshold, left, right, value, max_depth):
    """Tree arrays [M] -> per-leaf padded root-path steps.

    Returns dict with [L, D] step arrays (L = M//2+1 leaf slots, D = max_depth):
      sf: split feature of the ancestor; sthr: its threshold; sratio:
      cover(child)/cover(ancestor) for the path's child; sleft: whether the
      path goes left; svalid: step exists. Plus leaf_p0 [L] (class-0 prob),
      leaf_ok [L], leaf_cover_frac [L] (cover/root cover).
    Steps are ordered leaf -> root; order is irrelevant to the symmetric
    EXTEND polynomial.
    """
    m = feature.shape[0]
    d_max = max_depth
    cover = value.sum(-1)

    idx = jnp.arange(m)
    parent_buf = jnp.full((m + 1,), -1, jnp.int32)
    parent = parent_buf.at[jnp.where(left >= 0, left, m)].set(
        jnp.where(left >= 0, idx, -1).astype(jnp.int32)
    )
    parent = parent.at[jnp.where(right >= 0, right, m)].set(
        jnp.where(right >= 0, idx, -1).astype(jnp.int32)
    )
    parent = parent[:m]

    is_leaf = (feature < 0) & (cover > 0)
    n_slots = m // 2 + 1
    leaf_ids = jnp.argsort(~is_leaf, stable=True)[:n_slots].astype(jnp.int32)
    leaf_ok = is_leaf[leaf_ids]

    def walk(leaf):
        def step(carry, _):
            node = carry
            p = parent[node]
            ok = p >= 0
            psafe = jnp.maximum(p, 0)
            rec = (
                jnp.where(ok, feature[psafe], 0).astype(jnp.int32),
                jnp.where(ok, threshold[psafe], 0.0),
                jnp.where(ok, cover[node] / jnp.maximum(cover[psafe], 1e-30),
                          1.0),
                ok & (left[psafe] == node),
                ok,
            )
            return jnp.where(ok, psafe, node), rec

        _, recs = lax.scan(step, leaf, None, length=d_max)
        return recs

    sf, sthr, sratio, sleft, svalid = jax.vmap(walk)(leaf_ids)

    v0 = value[leaf_ids, 0]
    tot = jnp.maximum(value[leaf_ids].sum(-1), 1e-30)
    root_cover = jnp.maximum(cover[0], 1e-30)

    return {
        "sf": sf, "sthr": sthr, "sratio": sratio, "sleft": sleft,
        "svalid": svalid, "leaf_p0": v0 / tot, "leaf_ok": leaf_ok,
        "leaf_cover_frac": cover[leaf_ids] / root_cover,
    }


def _merge_path_features(paths, x, n_features):
    """Per (leaf, feature): presence, merged zero fraction z (product of cover
    ratios), and per-sample one fraction o (AND of branch indicators).

    Returns present [L, F], z [L, F], o [L, S, F].
    """
    sf, sratio, sthr, sleft, svalid = (
        paths["sf"], paths["sratio"], paths["sthr"], paths["sleft"],
        paths["svalid"],
    )
    l, d = sf.shape
    onehot = (sf[:, :, None] == jnp.arange(n_features)[None, None, :]) & (
        svalid[:, :, None]
    )  # [L, D, F]
    present = onehot.any(axis=1)
    z = jnp.prod(jnp.where(onehot, sratio[:, :, None], 1.0), axis=1)

    def sample_o(xs):  # xs: [F] one sample
        goes_left = xs[sf] <= sthr  # [L, D]
        ind = jnp.where(sleft, goes_left, ~goes_left)
        sat = jnp.where(onehot, ind[:, :, None], True)
        return jnp.all(sat, axis=1)  # [L, F]

    o = jax.vmap(sample_o, in_axes=0, out_axes=1)(x)  # [L, S, F]
    return present, z, o.astype(z.dtype)


def _extend_all(present, z, o, n_features):
    """Run the EXTEND polynomial over all (up to F) unique path features.

    present/z/o: [..., F]. Returns (w [..., F+2], l [...]) — the permutation
    weight vector and final path length (dummy element included).
    """
    shape = present.shape[:-1]
    f2 = n_features + 2
    i = jnp.arange(f2)

    w0 = jnp.zeros((*shape, f2), z.dtype).at[..., 0].set(1.0)
    l0 = jnp.ones(shape, z.dtype)  # dummy element counts 1

    def ext(carry, f):
        w, l = carry
        zf = z[..., f][..., None]
        of = o[..., f][..., None]
        pf = present[..., f]
        ln = l[..., None]
        # Functional form of the in-place EXTEND recurrence: position i keeps
        # z*w[i]*(l-i)/(l+1) and gains o*w[i-1]*i/(l+1) from below.
        stay = zf * w * (ln - i) / (ln + 1.0)
        up = of * jnp.concatenate(
            [jnp.zeros_like(w[..., :1]), w[..., :-1]], axis=-1
        ) * i / (ln + 1.0)
        w = jnp.where(pf[..., None], stay + up, w)
        l = l + pf.astype(l.dtype)
        return (w, l), None

    (w, l), _ = lax.scan(ext, (w0, l0), jnp.arange(n_features))
    return w, l


def _unwound_sum(w, l, z, o):
    """Sum of the path weights after UNWINDing one feature with fractions
    (z, o) — the inner loop of Tree SHAP's leaf accumulation, vectorized over
    the weight axis being implicit (runs the sequential recurrence over F+1
    positions).

    w: [..., F+2]; l: [...] path length (count incl. dummy); z,o: [...].
    """
    f2 = w.shape[-1]

    def step(carry, j):
        # iterate positions j = l-2 .. 0: run j over the static range high to
        # low, masking positions >= l-1.
        total, nxt = carry
        lm1 = l - 1.0
        active = (j <= lm1 - 1.0) & (lm1 > 0)
        wj = jnp.take(w, j.astype(jnp.int32), axis=-1)
        # o != 0 branch
        tmp = nxt * l / ((j + 1.0) * jnp.where(o == 0, 1.0, o))
        total_o = total + tmp
        nxt_o = wj - tmp * z * (lm1 - j) / l
        # o == 0 branch
        total_z = total + wj * l / (z * (lm1 - j))
        tot_new = jnp.where(o == 0, total_z, total_o)
        nxt_new = jnp.where(o == 0, nxt, nxt_o)
        total = jnp.where(active, tot_new, total)
        nxt = jnp.where(active, nxt_new, nxt)
        return (total, nxt), None

    # nxt starts at w[l-1]
    li = (l - 1.0).astype(jnp.int32)[..., None]
    nxt0 = jnp.take_along_axis(w, li, axis=-1)[..., 0]
    total0 = jnp.zeros_like(nxt0)
    js = jnp.arange(f2 - 2, -1, -1).astype(w.dtype)
    (total, _), _ = lax.scan(step, (total0, nxt0), js)
    return total


@functools.partial(jax.jit, static_argnames=("n_features",))
def tree_shap_single(paths, x, n_features):
    """phi [S, F] for one tree's class-0 leaf values."""
    present, z, o = _merge_path_features(paths, x, n_features)
    # broadcast z/present over samples: [L, S, F]
    zs = jnp.broadcast_to(z[:, None, :], o.shape)
    ps = jnp.broadcast_to(present[:, None, :], o.shape)

    w, l = _extend_all(ps, zs, o, n_features)  # [L, S, F+2], [L, S]

    def per_feature(f):
        u = _unwound_sum(w, l, zs[..., f], o[..., f])  # [L, S]
        phi_f = (o[..., f] - zs[..., f]) * u
        return jnp.where(ps[..., f], phi_f, 0.0)

    phi = jax.vmap(per_feature)(jnp.arange(n_features))  # [F, L, S]
    leaf_scale = jnp.where(paths["leaf_ok"], paths["leaf_p0"], 0.0)
    phi = jnp.einsum("fls,l->sf", phi, leaf_scale)
    return phi


def forest_shap_class0(forest, x, *, sample_chunk=None):
    """Mean over trees of per-tree class-0 Tree SHAP — the ensemble
    soft-vote's probability decomposition (what shap_values(X)[0] returns for
    a sklearn forest).

    forest: trees.Forest with [T, ...] axes. Trees run under lax.map so only
    one tree's O(L*S*F) workspace is live; chunk samples via ``sample_chunk``
    if even that is too large.
    """
    n_features = x.shape[1]
    t = forest.feature.shape[0]
    depth = int(forest.max_depth)

    def one_tree(args):
        fe, th, le, ri, va = args
        paths = extract_paths(fe, th, le, ri, va, depth)
        if sample_chunk is None:
            return tree_shap_single(paths, x, n_features)
        n = x.shape[0]
        pads = (-n) % sample_chunk
        xp = jnp.pad(x, ((0, pads), (0, 0)))
        chunks = xp.reshape(-1, sample_chunk, n_features)
        out = lax.map(
            lambda c: tree_shap_single(paths, c, n_features), chunks
        )
        return out.reshape(-1, n_features)[:n]

    phis = lax.map(
        one_tree,
        (forest.feature, forest.threshold, forest.left, forest.right,
         forest.value),
    )
    return jnp.mean(phis, axis=0)


def expected_p0(forest):
    """Base value E[p0] under path-dependent cover weighting, per tree then
    averaged — pairs with forest_shap_class0 for the local-accuracy check."""
    def one(args):
        fe, th, le, ri, va = args
        paths = extract_paths(fe, th, le, ri, va, int(forest.max_depth))
        return jnp.sum(
            jnp.where(paths["leaf_ok"],
                      paths["leaf_p0"] * paths["leaf_cover_frac"], 0.0)
        )

    vals = lax.map(
        one,
        (forest.feature, forest.threshold, forest.left, forest.right,
         forest.value),
    )
    return jnp.mean(vals)
