"""Path-dependent Tree SHAP as pure JAX (reference: shap.TreeExplainer's C
extension, feature_perturbation='tree_path_dependent', called at
/root/reference/experiment.py:517; SURVEY.md §2 table B).

Formulation: instead of the reference's sequential recursive EXTEND/UNWIND
walk, we use the leaf-parallel decomposition (the GPUTreeShap insight — see
PAPERS.md): each (leaf, sample) pair contributes independently. For a leaf's
root path, duplicate features merge multiplicatively into per-feature
(zero_fraction z_f, one_fraction o_f) with at most F unique entries; the
Shapley permutation weights come from one EXTEND polynomial pass over the F
feature slots and one UNWIND per present feature — O(F^2) per (leaf, sample),
F = 16. Leaves and samples ride vmap axes; trees are summed with lax.map so
only one tree's workspace is live at a time. This maps to the TPU VPU as large
elementwise/scan batches instead of pointer-chasing recursion.

Output convention matches the reference exactly: ``shap_values(X)[0]`` —
contributions to the *class-0 probability* of the soft-vote ensemble, an
[S, F] array (experiment.py:517 takes element [0] of the per-class list).

Local accuracy (sum_f phi_f(x) = p0(x) - E[p0]) is the built-in invariant the
tests enforce, alongside a brute-force subset-enumeration oracle on tiny trees.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flake16_framework_tpu.obs import costs as _costs
from flake16_framework_tpu.ops.trees import slice_trees, trim_nodes
from flake16_framework_tpu.resilience import ladder as _ladder


def extract_paths(feature, threshold, left, right, value, max_depth):
    """Tree arrays [M] -> per-leaf padded root-path steps.

    Returns dict with [L, D] step arrays (L = M//2+1 leaf slots, D = max_depth):
      sf: split feature of the ancestor; sthr: its threshold; sratio:
      cover(child)/cover(ancestor) for the path's child; sleft: whether the
      path goes left; svalid: step exists. Plus leaf_p0 [L] (class-0 prob),
      leaf_ok [L], leaf_cover_frac [L] (cover/root cover).
    Steps are ordered leaf -> root; order is irrelevant to the symmetric
    EXTEND polynomial.
    """
    m = feature.shape[0]
    d_max = max_depth
    cover = value.sum(-1)

    idx = jnp.arange(m)
    parent_buf = jnp.full((m + 1,), -1, jnp.int32)
    parent = parent_buf.at[jnp.where(left >= 0, left, m)].set(
        jnp.where(left >= 0, idx, -1).astype(jnp.int32)
    )
    parent = parent.at[jnp.where(right >= 0, right, m)].set(
        jnp.where(right >= 0, idx, -1).astype(jnp.int32)
    )
    parent = parent[:m]

    is_leaf = (feature < 0) & (cover > 0)
    n_slots = m // 2 + 1
    leaf_ids = jnp.argsort(~is_leaf, stable=True)[:n_slots].astype(jnp.int32)
    leaf_ok = is_leaf[leaf_ids]

    def walk(leaf):
        def step(carry, _):
            node = carry
            p = parent[node]
            ok = p >= 0
            psafe = jnp.maximum(p, 0)
            rec = (
                jnp.where(ok, feature[psafe], 0).astype(jnp.int32),
                jnp.where(ok, threshold[psafe], 0.0),
                jnp.where(ok, cover[node] / jnp.maximum(cover[psafe], 1e-30),
                          1.0),
                ok & (left[psafe] == node),
                ok,
            )
            return jnp.where(ok, psafe, node), rec

        _, recs = lax.scan(step, leaf, None, length=d_max)
        return recs

    sf, sthr, sratio, sleft, svalid = jax.vmap(walk)(leaf_ids)

    v0 = value[leaf_ids, 0]
    tot = jnp.maximum(value[leaf_ids].sum(-1), 1e-30)
    root_cover = jnp.maximum(cover[0], 1e-30)

    return {
        "sf": sf, "sthr": sthr, "sratio": sratio, "sleft": sleft,
        "svalid": svalid, "leaf_p0": v0 / tot, "leaf_ok": leaf_ok,
        "leaf_cover_frac": cover[leaf_ids] / root_cover,
    }


def _merge_path_features(paths, x, n_features):
    """Per (leaf, feature): presence, merged zero fraction z (product of cover
    ratios), and per-sample one fraction o (AND of branch indicators).

    Returns present [L, F], z [L, F], o [L, S, F].
    """
    sf, sratio, sthr, sleft, svalid = (
        paths["sf"], paths["sratio"], paths["sthr"], paths["sleft"],
        paths["svalid"],
    )
    l, d = sf.shape
    onehot = (sf[:, :, None] == jnp.arange(n_features)[None, None, :]) & (
        svalid[:, :, None]
    )  # [L, D, F]
    present = onehot.any(axis=1)
    z = jnp.prod(jnp.where(onehot, sratio[:, :, None], 1.0), axis=1)

    def sample_o(xs):  # xs: [F] one sample
        goes_left = xs[sf] <= sthr  # [L, D]
        ind = jnp.where(sleft, goes_left, ~goes_left)
        sat = jnp.where(onehot, ind[:, :, None], True)
        return jnp.all(sat, axis=1)  # [L, F]

    o = jax.vmap(sample_o, in_axes=0, out_axes=1)(x)  # [L, S, F]
    return present, z, o.astype(z.dtype)


def _extend_all(present, z, o, n_features):
    """Run the EXTEND polynomial over all (up to F) unique path features.

    present/z/o: [..., F]. Returns (w [..., F+2], l [...]) — the permutation
    weight vector and final path length (dummy element included).
    """
    shape = present.shape[:-1]
    f2 = n_features + 2
    i = jnp.arange(f2)

    w0 = jnp.zeros((*shape, f2), z.dtype).at[..., 0].set(1.0)
    l0 = jnp.ones(shape, z.dtype)  # dummy element counts 1

    def ext(carry, f):
        w, l = carry
        zf = z[..., f][..., None]
        of = o[..., f][..., None]
        pf = present[..., f]
        ln = l[..., None]
        # Functional form of the in-place EXTEND recurrence: position i keeps
        # z*w[i]*(l-i)/(l+1) and gains o*w[i-1]*i/(l+1) from below.
        stay = zf * w * (ln - i) / (ln + 1.0)
        up = of * jnp.concatenate(
            [jnp.zeros_like(w[..., :1]), w[..., :-1]], axis=-1
        ) * i / (ln + 1.0)
        w = jnp.where(pf[..., None], stay + up, w)
        l = l + pf.astype(l.dtype)
        return (w, l), None

    (w, l), _ = lax.scan(ext, (w0, l0), jnp.arange(n_features))
    return w, l


def _unwound_sum(w, l, z, o):
    """Sum of the path weights after UNWINDing one feature with fractions
    (z, o) — the inner loop of Tree SHAP's leaf accumulation, vectorized over
    the weight axis being implicit (runs the sequential recurrence over F+1
    positions).

    w: [..., F+2]; l: [...] path length (count incl. dummy); z,o: [...].
    """
    f2 = w.shape[-1]

    def step(carry, j):
        # iterate positions j = l-2 .. 0: run j over the static range high to
        # low, masking positions >= l-1.
        total, nxt = carry
        lm1 = l - 1.0
        active = (j <= lm1 - 1.0) & (lm1 > 0)
        wj = jnp.take(w, j.astype(jnp.int32), axis=-1)
        # o != 0 branch
        tmp = nxt * l / ((j + 1.0) * jnp.where(o == 0, 1.0, o))
        total_o = total + tmp
        nxt_o = wj - tmp * z * (lm1 - j) / l
        # o == 0 branch
        total_z = total + wj * l / (z * (lm1 - j))
        tot_new = jnp.where(o == 0, total_z, total_o)
        nxt_new = jnp.where(o == 0, nxt, nxt_o)
        total = jnp.where(active, tot_new, total)
        nxt = jnp.where(active, nxt_new, nxt)
        return (total, nxt), None

    # nxt starts at w[l-1]
    li = (l - 1.0).astype(jnp.int32)[..., None]
    nxt0 = jnp.take_along_axis(w, li, axis=-1)[..., 0]
    total0 = jnp.zeros_like(nxt0)
    js = jnp.arange(f2 - 2, -1, -1).astype(w.dtype)
    (total, _), _ = lax.scan(step, (total0, nxt0), js)
    return total


@functools.partial(jax.jit, static_argnames=("n_features",))
def tree_shap_single(paths, x, n_features):
    """phi [S, F] for one tree's class-0 leaf values."""
    present, z, o = _merge_path_features(paths, x, n_features)
    # broadcast z/present over samples: [L, S, F]
    zs = jnp.broadcast_to(z[:, None, :], o.shape)
    ps = jnp.broadcast_to(present[:, None, :], o.shape)

    w, l = _extend_all(ps, zs, o, n_features)  # [L, S, F+2], [L, S]

    def per_feature(f):
        u = _unwound_sum(w, l, zs[..., f], o[..., f])  # [L, S]
        phi_f = (o[..., f] - zs[..., f]) * u
        return jnp.where(ps[..., f], phi_f, 0.0)

    phi = jax.vmap(per_feature)(jnp.arange(n_features))  # [F, L, S]
    leaf_scale = jnp.where(paths["leaf_ok"], paths["leaf_p0"], 0.0)
    phi = jnp.einsum("fls,l->sf", phi, leaf_scale)
    return phi


def forest_shap_class0(forest, x, *, sample_chunk=None, impl="auto",
                       tree_chunk=None, _trim=True):
    """Mean over trees of per-tree class-0 Tree SHAP — the ensemble
    soft-vote's probability decomposition (what shap_values(X)[0] returns for
    a sklearn forest).

    forest: trees.Forest with [T, ...] axes.

    ``impl``: "pallas" (the TPU kernel below), "xla" (the lax.map/vmap
    formulation above), or "auto" — pallas on TPU, xla elsewhere (the kernel
    runs anywhere via the Pallas interpreter, but interpret mode is only
    meant for tests). For "xla", trees run under lax.map so only one tree's
    O(L*S*F) workspace is live; chunk samples via ``sample_chunk`` if even
    that is too large.

    ``tree_chunk`` splits the forest into ceil(T/tree_chunk)-sized slices
    explained in SEPARATE device dispatches (per-tree phis are additive, so
    the weighted recombination is exact). This bounds single-dispatch
    duration — the TPU tunnel faults on multi-minute dispatches (PROFILE.md)
    — unlike ``sample_chunk``, which only bounds the live workspace *inside*
    one dispatch.

    Both impls dispatch through module-level jits keyed on static shapes, so
    repeated explains (the 2 reference configs, the bench's steady-state
    timing) reuse one compiled program instead of re-lowering per call.
    """
    # Trim node-slot padding before anything else: the per-(leaf, sample)
    # workspace scales with M//2+1 leaf SLOTS, and fit-time max_nodes is a
    # worst-case bound typically several times the grown size. One host
    # sync of max(n_nodes), rounded up to keep the jit cache small; ONLY at
    # the top level — per-chunk re-trims would give chunks different M
    # buckets and recompile the SHAP program per chunk.
    if _trim:
        # Degradation ladder (resilience/ladder.py): after an OOM /
        # envelope-overrun the halved bounds shrink the live workspace and
        # the single-dispatch duration. Top level only — the tree_chunk
        # recursion below passes already-halved bounds with _trim=False.
        sample_chunk = _ladder.halved(sample_chunk)
        tree_chunk = _ladder.halved(tree_chunk)
        m = forest.feature.shape[-1]
        n_used = int(jax.device_get(jnp.max(forest.n_nodes)))
        m_trim = min(m, max(128, -(-n_used // 128) * 128))
        if m_trim < m:
            forest = trim_nodes(forest, m_trim)

    t_total = forest.feature.shape[0]
    if tree_chunk is not None and tree_chunk < t_total:
        acc = None
        for lo in range(0, t_total, tree_chunk):
            sub = slice_trees(forest, lo, lo + tree_chunk)
            c = sub.feature.shape[0]
            phi = forest_shap_class0(sub, x, sample_chunk=sample_chunk,
                                     impl=impl, _trim=False) * c
            # Deliberate per-chunk block: tree_chunk exists to BOUND single
            # dispatch duration (device-fault envelope), so chunks must not
            # pipeline into one long in-flight tail.
            phi.block_until_ready()  # f16lint: disable=J402
            acc = phi if acc is None else acc + phi
        return acc / t_total
    auto = impl == "auto"
    if auto:
        impl = ("pallas" if jax.default_backend() == "tpu"
                and not _PALLAS_AUTO_BROKEN[0] else "xla")
    depth = int(forest.max_depth)  # static by construction (fit-time bound)
    if impl == "pallas":
        interpret = jax.default_backend() != "tpu"
        try:
            # block INSIDE the try: jit dispatch is async, so a device
            # fault would otherwise surface at the caller's sync, outside
            # this handler
            return jax.block_until_ready(_pallas_forest_shap(
                forest, x, depth=depth, interpret=interpret))
        except Exception as e:  # Mosaic lowering/runtime errors share no base
            # auto mode must never cost the SHAP stage a whole bench run
            # on the kernel's first-ever device attempt: fall back to the
            # XLA formulation (same values — interpret-mode equality is
            # test-pinned), remember the failure so chunked calls do not
            # re-attempt the broken compile per chunk, and say so.
            # Explicit impl="pallas" still raises — shap_equiv needs the
            # real error.
            if not auto:
                raise
            import sys

            # The pallas->xla rung of the degradation ladder: classifies
            # the failure, emits the fault/degrade obs event, and sets the
            # sticky per-process flag (resilience/ladder.py).
            _ladder.mark_pallas_broken(e)
            print(f"treeshap: pallas kernel failed on "
                  f"{jax.default_backend()} ({type(e).__name__}: "
                  f"{str(e)[:200]}); auto-falling back to impl='xla'",
                  file=sys.stderr, flush=True)
            impl = "xla"
    if impl != "xla":
        raise ValueError(f"unknown Tree SHAP impl {impl!r}")
    return _xla_forest_shap(forest, x, depth=depth, sample_chunk=sample_chunk)


class _PallasBrokenProxy:
    """Back-compat view of the old sticky ``_PALLAS_AUTO_BROKEN = [False]``
    flag, now owned by the degradation ladder (resilience/ladder.py
    ``pallas_broken``): after an auto-mode kernel failure, every later auto
    call (including the remaining chunks of a tree_chunk loop) goes straight
    to the XLA formulation instead of re-running the failed Mosaic compile
    per chunk. Reads/writes of ``_PALLAS_AUTO_BROKEN[0]`` (tests, external
    scripts) keep working and see/steer the ladder state."""

    def __getitem__(self, i):
        if i != 0:
            raise IndexError(i)
        return _ladder.state().pallas_broken

    def __setitem__(self, i, v):
        if i != 0:
            raise IndexError(i)
        _ladder.state().pallas_broken = bool(v)

    def __repr__(self):
        return f"[{_ladder.state().pallas_broken}]"


_PALLAS_AUTO_BROKEN = _PallasBrokenProxy()


@functools.partial(jax.jit, static_argnames=("depth", "sample_chunk"))
def _xla_forest_shap(forest, x, *, depth, sample_chunk=None):
    n_features = x.shape[1]

    def one_tree(args):
        fe, th, le, ri, va = args
        paths = extract_paths(fe, th, le, ri, va, depth)
        if sample_chunk is None:
            return tree_shap_single(paths, x, n_features)
        n = x.shape[0]
        pads = (-n) % sample_chunk
        xp = jnp.pad(x, ((0, pads), (0, 0)))
        chunks = xp.reshape(-1, sample_chunk, n_features)
        out = lax.map(
            lambda c: tree_shap_single(paths, c, n_features), chunks
        )
        return out.reshape(-1, n_features)[:n]

    phis = lax.map(
        one_tree,
        (forest.feature, forest.threshold, forest.left, forest.right,
         forest.value),
    )
    return jnp.mean(phis, axis=0)


# --------------------------------------------------------------------------
# Pallas TPU kernel
# --------------------------------------------------------------------------
#
# Layout (north star: "rewrite shap.TreeExplainer's tree-path-dependent value
# computation as a Pallas kernel"; parallelization over (tree, sample) blocks
# is the GPUTreeShap decomposition — PAPERS.md):
#
#   grid = (sample_block, tree, leaf_block); the output block [F, SBLK]
#   depends only on the sample block, so the (tree, leaf) dims accumulate
#   into a resident VMEM block. Samples ride the 128-wide lane axis; the
#   EXTEND weight vector rides sublanes ([F+2, SBLK] tiles). A leaf's D path
#   steps are merged into per-feature (zero fraction, one fraction) with
#   three tiny [F, D] x [D, SBLK] MXU matmuls (one-hot selects instead of
#   gathers, which TPU lacks along sublanes). Per-tree real-leaf counts are
#   scalar-prefetched so padded leaf blocks predicate off.

# Env-overridable for the hardware tuning session (read at import, like
# the tree-grower knobs — tools/hw_probe.py runs each combo in a fresh
# subprocess). Defaults are the shipped configuration.
_SBLK = int(os.environ.get("F16_SHAP_SBLK", "128"))
_LBLK = int(os.environ.get("F16_SHAP_LBLK", "8"))


def _shap_kernel(n_leaves_ref, sf, sthr, sratio, sleft, svalid, leaf_p0,
                 leaf_ok, xt, out, *, n_features, depth):
    sb, t, lb = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    f32 = jnp.float32
    fp2 = n_features + 2

    @pl.when((t == 0) & (lb == 0))
    def _():
        out[:] = jnp.zeros_like(out)

    block_has_leaves = lb * _LBLK < n_leaves_ref[t]

    @pl.when(block_has_leaves)
    def _():
        x_fs = xt[:]                                   # [F, SBLK]
        iota_f = lax.broadcasted_iota(f32, (n_features, depth), 0)
        iota_i = lax.broadcasted_iota(f32, (fp2, 1), 0)
        # One-hot row selects throughout, NEVER dynamic VMEM indexing:
        # a traced scalar index (a[leaf, :], w[li, :]) is the classic
        # construct that passes the Pallas interpreter but trips Mosaic
        # lowering on real silicon; compares + dots lower unconditionally.
        iota_lb = lax.broadcasted_iota(f32, (1, _LBLK), 1)

        def one_leaf(leaf, acc):
            onehot_l = (iota_lb == leaf.astype(f32)).astype(f32)  # [1,LBLK]

            def sel_l(ref):
                """[D] row of one path tensor at ``leaf``: elementwise
                mask + sublane reduce, NOT a dot — the MXU's default bf16
                pass would round thresholds/ratios before use (the known
                TPU matmul-precision trap, trees.py)."""
                return jnp.sum(ref[0].astype(f32) * onehot_l.T, axis=0)

            sf_l = sel_l(sf)                           # [D] f32 (small ints)
            svalid_l = sel_l(svalid)
            onehot_fd = (sf_l[None, :] == iota_f) & (svalid_l[None, :] > 0)
            onehot_fd = onehot_fd.astype(f32)          # [F, D]

            # Merged per-feature fractions: z (cover products, via logs),
            # presence, and the per-sample one-fraction o (AND of branch
            # indicators along the path, via a zero count).
            # HIGHEST on every data-carrying dot: the one-hot operand is
            # bf16-exact but the MXU's default pass would round the DATA
            # side (logs, x values) before accumulating — the same trap
            # the tree growers pin (trees.py precision=HIGHEST).
            hi = lax.Precision.HIGHEST
            logr = jnp.log(jnp.maximum(sel_l(sratio), 1e-30))
            z = jnp.exp(
                jnp.dot(onehot_fd, logr[:, None],
                        preferred_element_type=f32, precision=hi)
            )                                          # [F, 1]
            present = (
                jnp.dot(onehot_fd, jnp.ones((depth, 1), f32),
                        preferred_element_type=f32, precision=hi) > 0
            )                                          # [F, 1]

            x_sel = jnp.dot(onehot_fd.T, x_fs,
                            preferred_element_type=f32,
                            precision=hi)              # [D, SBLK]
            goes_left = x_sel <= sel_l(sthr)[:, None]
            ind = jnp.where(sel_l(sleft)[:, None] > 0, goes_left,
                            ~goes_left)
            miss = jnp.dot(onehot_fd, 1.0 - ind.astype(f32),
                           preferred_element_type=f32, precision=hi)
            o = (miss == 0).astype(f32)                # [F, SBLK]

            # EXTEND: fold each present feature into the permutation-weight
            # vector w [F+2, SBLK]; path length l is sample-independent.
            w0 = jnp.zeros((fp2, _SBLK), f32).at[0, :].set(1.0)
            iota_fx = lax.broadcasted_iota(f32, (1, n_features), 1)

            def ext(f, carry):
                w, l = carry
                onehot_fx = (iota_fx == f.astype(f32)).astype(f32)  # [1,F]
                # elementwise mask + reduce (no MXU rounding of z/o)
                pf = jnp.sum(present.astype(f32) * onehot_fx.T) > 0
                zf = jnp.sum(z * onehot_fx.T)
                of = jnp.sum(o * onehot_fx.T, axis=0)[None, :]  # [1, SBLK]
                stay = zf * w * (l - iota_i) / (l + 1.0)
                w_shift = jnp.concatenate(
                    [jnp.zeros((1, _SBLK), f32), w[:-1, :]], axis=0
                )
                up = of * w_shift * iota_i / (l + 1.0)
                return (jnp.where(pf, stay + up, w),
                        jnp.where(pf, l + 1.0, l))

            w, l = lax.fori_loop(0, n_features, ext, (w0, jnp.float32(1.0)))

            # UNWIND all features at once, j from high to low; total is the
            # sum of unwound weights, phi_f = (o_f - z_f) * total * leaf_p0.
            onehot_li = (iota_i == (l - 1.0)).astype(f32)   # [F+2, 1]
            w_l = jnp.sum(w * onehot_li, axis=0)            # [SBLK]
            nxt0 = jnp.broadcast_to(w_l[None, :], (n_features, _SBLK))
            zb = jnp.broadcast_to(z, (n_features, _SBLK))
            zb = jnp.maximum(zb, 1e-30)

            def unwind(jj, carry):
                total, nxt = carry
                j = jnp.float32(fp2 - 2) - jj          # static countdown
                activ = (j <= l - 2.0)
                onehot_j = (iota_i == j).astype(f32)   # [F+2, 1]
                wj_row = jnp.sum(w * onehot_j, axis=0)  # [SBLK]
                wj = jnp.broadcast_to(wj_row[None, :],
                                      (n_features, _SBLK))
                o_safe = jnp.where(o == 0, 1.0, o)
                tmp = nxt * l / ((j + 1.0) * o_safe)
                total_o = total + tmp
                nxt_o = wj - tmp * zb * (l - 1.0 - j) / l
                total_z = total + wj * l / (zb * (l - 1.0 - j))
                tot_new = jnp.where(o == 0, total_z, total_o)
                nxt_new = jnp.where(o == 0, nxt, nxt_o)
                total = jnp.where(activ, tot_new, total)
                nxt = jnp.where(activ, nxt_new, nxt)
                return total, nxt

            total, _ = lax.fori_loop(
                0, fp2 - 1, unwind,
                (jnp.zeros((n_features, _SBLK), f32), nxt0),
            )

            scale = (jnp.sum(leaf_p0[0] * onehot_l[0])
                     * jnp.sum(leaf_ok[0] * onehot_l[0]))
            contrib = jnp.where(
                present & (l > 1.0), (o - zb) * total * scale, 0.0
            )
            return acc + contrib

        acc = lax.fori_loop(
            0, _LBLK, one_leaf, jnp.zeros((n_features, _SBLK), f32)
        )
        out[:] += acc


@functools.partial(jax.jit, static_argnames=("depth", "interpret"))
def _pallas_forest_shap(forest, x, *, depth, interpret):
    """[F, S]-accumulating Pallas launch over (sample, tree, leaf) blocks;
    returns the per-sample mean over trees, transposed to [S, F]."""
    t, m = forest.feature.shape
    s, n_features = x.shape
    # Pad the feature (sublane) axis to the f32 tile minimum; padded feature
    # rows never match a path step (their one-hot rows stay empty), so their
    # contributions are exactly zero and are sliced off at the end.
    n_feat_k = max(8, n_features + (-n_features) % 8)

    paths = jax.vmap(
        lambda fe, th, le, ri, va: extract_paths(fe, th, le, ri, va, depth)
    )(forest.feature, forest.threshold, forest.left, forest.right,
      forest.value)

    l_slots = paths["sf"].shape[1]
    l_pad = (-l_slots) % _LBLK
    s_pad = (-s) % _SBLK

    def pad_l(a, fill=0):
        return jnp.pad(a, ((0, 0), (0, l_pad)) + ((0, 0),) * (a.ndim - 2),
                       constant_values=fill)

    sf = pad_l(paths["sf"]).astype(jnp.int32)
    sthr = pad_l(paths["sthr"]).astype(jnp.float32)
    sratio = pad_l(paths["sratio"], 1).astype(jnp.float32)
    sleft = pad_l(paths["sleft"]).astype(jnp.int32)
    svalid = pad_l(paths["svalid"]).astype(jnp.int32)
    leaf_p0 = pad_l(paths["leaf_p0"]).astype(jnp.float32)
    leaf_ok = pad_l(paths["leaf_ok"]).astype(jnp.float32)
    n_leaves = jnp.sum(paths["leaf_ok"], axis=1).astype(jnp.int32)  # [T]

    xt = jnp.pad(x.T.astype(jnp.float32),
                 ((0, n_feat_k - n_features), (0, s_pad)))

    lt = (l_slots + l_pad) // _LBLK
    st = (s + s_pad) // _SBLK

    # Index maps receive the scalar-prefetch ref as a trailing argument.
    path_spec = pl.BlockSpec(
        (1, _LBLK, depth), lambda sb, t_, lb, nl: (t_, lb, 0)
    )
    leaf_spec = pl.BlockSpec((1, _LBLK), lambda sb, t_, lb, nl: (t_, lb))

    out = pl.pallas_call(
        functools.partial(_shap_kernel, n_features=n_feat_k, depth=depth),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(st, t, lt),
            in_specs=[
                path_spec, path_spec, path_spec, path_spec, path_spec,
                leaf_spec, leaf_spec,
                pl.BlockSpec((n_feat_k, _SBLK),
                             lambda sb, t_, lb, nl: (0, sb)),
            ],
            out_specs=pl.BlockSpec((n_feat_k, _SBLK),
                                   lambda sb, t_, lb, nl: (0, sb)),
        ),
        out_shape=jax.ShapeDtypeStruct((n_feat_k, s + s_pad), jnp.float32),
        interpret=interpret,
    )(n_leaves, sf, sthr, sratio, sleft, svalid, leaf_p0, leaf_ok, xt)

    return out[:n_features, :s].T / t


# Cost attribution (obs/costs.py): the two explain programs are the SHAP
# stage's compiled kernels; the driver (forest_shap_class0) dispatches them
# from host, so the wrapper sees concrete arrays and can AOT-compile.
_xla_forest_shap = _costs.instrument(
    _xla_forest_shap, "shap.xla_forest",
    static_argnames=("depth", "sample_chunk"))
_pallas_forest_shap = _costs.instrument(
    _pallas_forest_shap, "shap.pallas_forest",
    static_argnames=("depth", "interpret"))


def expected_p0(forest):
    """Base value E[p0] under path-dependent cover weighting, per tree then
    averaged — pairs with forest_shap_class0 for the local-accuracy check."""
    def one(args):
        fe, th, le, ri, va = args
        paths = extract_paths(fe, th, le, ri, va, int(forest.max_depth))
        return jnp.sum(
            jnp.where(paths["leaf_ok"],
                      paths["leaf_p0"] * paths["leaf_cover_frac"], 0.0)
        )

    vals = lax.map(
        one,
        (forest.feature, forest.threshold, forest.left, forest.right,
         forest.value),
    )
    return jnp.mean(vals)
