"""Tunnel-relay liveness: the one definition of the relay port and its
cheap check, shared by bench.py and tools/recovery_watch.py.

The TPU in this environment is reached through a local relay; when its
host side dies, every jax process hangs forever at backend init, so
liveness must be established WITHOUT jax — a TCP listener probe via
``ss -tln``. Decisive only where the relay is actually the device path
(callers gate on the axon hook env)."""

import subprocess

RELAY_PORT = "8082"


def relay_listener_up(timeout=10):
    """True/False for a listener on the relay port; None when ``ss`` itself
    is unavailable (callers must treat None as unknown, not down)."""
    try:
        r = subprocess.run(["ss", "-tln"], capture_output=True, text=True,
                           timeout=timeout)
        return (":" + RELAY_PORT) in r.stdout
    except Exception:
        return None
