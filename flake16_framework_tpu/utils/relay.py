"""Tunnel-relay liveness: the one definition of the relay port and its
cheap check, shared by bench.py and tools/recovery_watch.py.

The TPU in this environment is reached through a local relay; when its
host side dies, every jax process hangs forever at backend init, so
liveness must be established WITHOUT jax — a TCP listener probe via
``ss -tln``, falling back to a direct loopback connect when ``ss`` is
unavailable (minimal containers). Decisive only where the relay is
actually the device path (callers gate on the axon hook env)."""

import socket
import subprocess

RELAY_PORT = "8082"


def relay_listener_up(timeout=10):
    """True/False for a listener on the relay port; None only when NEITHER
    probe can decide (callers must treat None as unknown, not down).

    Probe order: ``ss -tln`` (no connection made — a listener under
    connect backpressure still reads as up); when ``ss`` is missing or
    fails, a direct ``socket.create_connection`` to the loopback port —
    connect succeeds => up, connection refused => decisively down, any
    other socket error (timeout, no route) => unknown."""
    try:
        r = subprocess.run(["ss", "-tln"], capture_output=True, text=True,
                           timeout=timeout)
        if r.returncode == 0:
            return (":" + RELAY_PORT) in r.stdout
    except Exception:
        pass
    try:
        conn = socket.create_connection(("127.0.0.1", int(RELAY_PORT)),
                                        timeout=min(timeout, 3))
    except ConnectionRefusedError:
        return False
    except OSError:
        return None
    conn.close()
    return True
