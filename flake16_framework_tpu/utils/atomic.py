"""atomic_write — the one sanctioned durable-artifact writer (ISSUE 11).

Every artifact that must survive a process kill (scores/SHAP pickles,
timing/quarantine sidecars, serve registry index, obs manifest, bench
outputs) goes through this function. The contract is full
crash-consistency, one notch stronger than the tmp+``os.replace`` idiom
scattered through the pre-ISSUE-11 tree:

- the payload is written to a ``tempfile.mkstemp`` sibling in the SAME
  directory (same filesystem, so the final rename is atomic);
- the file is flushed and ``os.fsync``'d BEFORE the rename — without
  this, a rename can land while the data blocks are still dirty, and a
  power cut yields a zero-length "committed" artifact;
- ``os.replace`` publishes it atomically;
- the containing directory is fsync'd so the rename itself is durable.

f16lint's J701 rule flags write-mode ``open()`` on any other package
path, so new artifact writers cannot silently regress to torn writes.
"""

import contextlib
import os
import tempfile


def _fsync_dir(dirname):
    """Make a just-completed rename durable. Best-effort: some
    filesystems (and non-POSIX hosts) refuse O_RDONLY fsync on a dir."""
    try:
        dfd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)


@contextlib.contextmanager
def atomic_write(path, mode="wb", *, fsync=True, **open_kw):
    """Context manager yielding a file object; on clean exit the payload
    is fsync'd and atomically renamed onto ``path``. On ANY exception the
    temp file is removed and ``path`` is untouched — a crashed writer can
    never leave a torn artifact, only the previous complete one.

    ``mode`` is "wb" (default) or "w" (text; pass ``encoding=`` through
    ``open_kw``). ``fsync=False`` keeps the atomic-rename property but
    skips the durability syncs — for large, cheaply-recomputed artifacts
    where the caller explicitly trades durability for wall time.
    """
    path = os.fspath(path)
    dirname = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(
        dir=dirname, prefix=os.path.basename(path) + ".", suffix=".tmp")
    try:
        # mkstemp creates 0600; artifacts are shared read like any
        # open()-created file would have been.
        os.chmod(tmp, 0o644)
        with os.fdopen(fd, mode, **open_kw) as out:
            yield out
            out.flush()
            if fsync:
                os.fsync(out.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if fsync:
        _fsync_dir(dirname)
