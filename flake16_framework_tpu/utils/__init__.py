"""utils — host-side helpers with no jax dependency at import time.

- atomic.py — ``atomic_write``, the sanctioned durable-artifact writer
  (tmp + fsync + ``os.replace``; f16lint J701 flags bypasses)
- relay.py  — TPU-tunnel liveness diagnosis
- synth.py  — synthetic reference-schema dataset generation
"""

from flake16_framework_tpu.utils.atomic import atomic_write  # noqa: F401
