"""Synthetic ``tests.json`` generation.

The reference's real dataset comes from re-running 26 projects' test suites 5,001
times each (SURVEY.md §0) — not reproducible here. For unit tests and benchmarks we
generate a dataset with the same *shape and statistics*: 26 projects, the 16
Flake16 features with count-like heavy-tailed distributions (coverage line counts,
rusage counters, static-analysis metrics), heavy class imbalance (flaky tests are
rare), and labels 0/1/2 per the reference encoding with a weak learnable signal so
classifier comparisons are meaningful.

Schema matches README.rst:53-76: ``{proj: {nid: [req_runs, label, *16 features]}}``.
"""

import json

import numpy as np

from flake16_framework_tpu.constants import NON_FLAKY, OD_FLAKY, FLAKY


def make_dataset(n_tests=2000, n_projects=26, nod_frac=0.06, od_frac=0.04,
                 seed=0, nod_bump=0.8, od_bump=0.5, noise_sigma=0.4):
    """Return (features [N,16] float, labels [N] int, project_ids [N] int).

    ``nod_bump``/``od_bump``/``noise_sigma`` control class separability: the
    defaults give the weak signal unit tests want; parity harnesses raise the
    bumps (and lower the noise) so per-config F1 is stable enough for a
    +/-0.01 comparison to be meaningful (at the default signal the sklearn
    baseline's own seed-to-seed F1 spread exceeds 0.03)."""
    rng = np.random.RandomState(seed)

    labels = rng.choice(
        [NON_FLAKY, OD_FLAKY, FLAKY], size=n_tests,
        p=[1.0 - nod_frac - od_frac, od_frac, nod_frac]
    )
    project_ids = np.sort(rng.randint(0, n_projects, size=n_tests))

    # Count-like base features: lognormal magnitudes, rounded like the real
    # coverage/rusage/static counts (columns per constants.FEATURE_NAMES).
    base = rng.lognormal(mean=3.0, sigma=1.2, size=(n_tests, 16))
    scale = np.array([200, 50, 150, 0.01, 30, 20, 5, 1, 1e4,
                      1, 3, 2, 50, 2, 10, 1.0])
    feats = base * scale[None, :]

    # Weak signal: flaky tests skew slow/big (longer runtime, more coverage,
    # more IO) — mirrors the study's SHAP findings that runtime/IO dominate.
    bump = 1.0 + nod_bump * (labels == FLAKY) + od_bump * (labels == OD_FLAKY)
    noise = rng.lognormal(0.0, noise_sigma, size=(n_tests, 16))
    feats = feats * (bump[:, None] * noise)

    int_cols = [0, 1, 2, 4, 5, 6, 7, 9, 10, 11, 13, 14]
    feats[:, int_cols] = np.round(feats[:, int_cols])
    feats[:, 8] = np.round(feats[:, 8])  # Max. Memory in KB
    feats[:, 15] = np.clip(100.0 - feats[:, 15], 0, 100)  # Maintainability index

    return feats, labels.astype(np.int32), project_ids.astype(np.int32)


def make_tests_json(path=None, n_tests=2000, n_projects=26, seed=0,
                    names=None):
    """Write (or return) a reference-schema tests.json. ``names`` replaces
    the synthetic ``projectNN`` keys (e.g. with the real subject registry
    names, so the figures verb's subject join works on synthetic data)."""
    if names is not None:
        assert len(names) == n_projects, (len(names), n_projects)
    feats, labels, project_ids = make_dataset(
        n_tests=n_tests, n_projects=n_projects, seed=seed
    )
    rng = np.random.RandomState(seed + 1)

    tests = {}
    for pid in range(n_projects):
        rows = np.flatnonzero(project_ids == pid)
        if rows.size == 0:
            continue
        proj = f"project{pid:02d}" if names is None else names[pid]
        tests_proj = {}
        for j, r in enumerate(rows):
            req_runs = int(rng.randint(1, 2500)) if labels[r] != NON_FLAKY else 0
            tests_proj[f"tests/test_{proj}.py::test_{j:04d}"] = [
                req_runs, int(labels[r]), *[float(x) for x in feats[r]]
            ]
        tests[proj] = tests_proj

    if path is not None:
        from flake16_framework_tpu.utils.atomic import atomic_write

        with atomic_write(path, "w") as fd:
            json.dump(tests, fd, indent=4)

    return tests
