"""Dataset loading: ``tests.json`` -> fixed-shape arrays.

Semantics match the reference loader (/root/reference/experiment.py:410-427):
iterate projects in file order, then tests in file order; features are the
per-test tuple minus the leading (req_runs, label); labels are the raw encoded
label compared against the positive flaky label; projects expand to one entry
per test. The TPU build additionally returns integer project ids (for on-device
segment reductions) alongside the string array.
"""

import json

import numpy as np

from flake16_framework_tpu.constants import N_FEATURES


def load_tests(tests_file):
    with open(tests_file, "r") as fd:
        return json.load(fd)


def tests_to_arrays(tests):
    """tests dict -> (features [N,16] f64, labels_raw [N] i32, projects [N] str,
    project_names list, project_ids [N] i32).

    ``labels_raw`` keeps the 0/1/2 encoding; callers binarize against a flaky
    label (reference experiment.py:424) so one load serves both NOD and OD
    configs.
    """
    features, labels, projects = [], [], []

    for proj, tests_proj in tests.items():
        projects += [proj] * len(tests_proj)

        for (_, label_nid, *features_nid) in tests_proj.values():
            features.append(features_nid)
            labels.append(label_nid)

    features = np.asarray(features, dtype=np.float64).reshape(-1, N_FEATURES)
    labels = np.asarray(labels, dtype=np.int32)
    projects = np.asarray(projects)

    project_names = list(dict.fromkeys(projects.tolist()))
    name_to_id = {p: i for i, p in enumerate(project_names)}
    project_ids = np.asarray([name_to_id[p] for p in projects], dtype=np.int32)

    return features, labels, projects, project_names, project_ids


def load_feat_lab_proj(flaky_label, feature_set, tests_file):
    """Reference-shaped loader (experiment.py:410-427): returns
    (features[:, feature_set], labels == flaky_label, projects)."""
    features, labels, projects, _, _ = tests_to_arrays(load_tests(tests_file))
    return features[:, list(feature_set)], labels == flaky_label, projects
