"""f16race — the concurrency & shared-state rule pack (C101–C503).

The third static-analysis layer (PROFILE.md "Concurrency audit"):
f16lint proves AST hygiene, f16audit proves IR-level device contracts,
f16race proves the *host-side threaded* substrate — the microbatcher
dispatcher pool, admission queue, SLO monitor, metrics exporter,
flight-recorder ring, supervisor — keeps a coherent locking story.
Built on analysis/concurrency.py (thread topology + lock-set model,
RacerD-style compositional, no whole-program aliasing); the same model
feeds obs/lockwatch.py's runtime reconciliation.

Catalog:

- C101 (error): shared mutable state — a ``self.`` attribute, module
  global (including ``G.attr``/``G[k]`` mutation), or closure cell —
  written from >= 2 thread roots (a multi-instance root, e.g. a
  dispatcher pool spawned in a loop, counts as two writers) with an
  empty or inconsistent guard set across the writes. ``__init__``
  writes are exempt (happens-before thread start), as are assignments
  installing the sync primitive itself.
- C201 (error): lock-order inversion — a cycle in the project-wide
  lock-order graph (lock B acquired while A is held, lexically or
  through resolvable calls via per-function may-acquire summaries).
  The finding names every lock in the cycle.
- C301 (warning): blocking call (``.result()``/``.join()``/``.wait()``
  on a foreign object, ``time.sleep``, subprocess/socket/file I/O,
  ``jax.*`` device dispatch) while holding a lock in J601's hot-path
  scope (serve/batcher.py, serve/queue.py, ``@hot_path`` functions).
  ``cond.wait()`` on the *held* condition is exempt — it releases.
- C401 (warning): non-async-signal-safe work in a ``signal.signal``
  handler: lock acquisition, printing/logging/file I/O, subprocess,
  telemetry emission, or blocking waits. ``Event.set()``-style flag
  flips, ``os.kill``/``sys.exit``, and plain assignments are safe.
- C501 (warning): ``os.fork()`` in a module that starts threads — the
  child inherits locked locks without their owner threads.
- C502 (warning): ``multiprocessing`` Process/Pool in a thread-starting
  module — the default fork start method snapshots foreign locks
  mid-flight (use the spawn context, or fork before threading).
- C503 (warning): ``subprocess.*(..., preexec_fn=...)`` — the hook runs
  between fork and exec where only async-signal-safe code is legal.
"""

from flake16_framework_tpu.analysis import concurrency as conc
from flake16_framework_tpu.analysis.engine import ERROR, WARNING, RuleInfo

RULES = {
    "C101": RuleInfo(
        "C101", ERROR,
        "shared state written from >=2 thread roots without a "
        "consistent lock"),
    "C201": RuleInfo(
        "C201", ERROR,
        "lock-order inversion cycle (potential deadlock)"),
    "C301": RuleInfo(
        "C301", WARNING,
        "blocking call while holding a hot-path lock"),
    "C401": RuleInfo(
        "C401", WARNING,
        "non-async-signal-safe work in a signal handler"),
    "C501": RuleInfo(
        "C501", WARNING,
        "os.fork() in a module that starts threads"),
    "C502": RuleInfo(
        "C502", WARNING,
        "multiprocessing (fork start method) in a thread-starting "
        "module"),
    "C503": RuleInfo(
        "C503", WARNING,
        "subprocess preexec_fn runs between fork and exec"),
}

# C301 scope — J601's hot-path surface (rules_jax keeps the same list).
_HOT_MODULES = ("serve/batcher.py", "serve/queue.py")

_BLOCK_DOTTED = {"time.sleep", "os.system", "os.read", "os.write",
                 "select.select"}
_BLOCK_PREFIXES = ("subprocess.", "socket.", "jax.")
_BLOCK_ATTRS = {"result", "join"}

_SIGNAL_SAFE_ATTRS = {"set", "is_set", "kill", "exit", "_exit", "append"}
_SIGNAL_SAFE_DOTTED = {"os.kill", "os._exit", "sys.exit", "signal.signal",
                       "signal.getsignal", "time.time", "time.monotonic"}

_MP_SPAWNERS = {"Process", "Pool"}


def _display(key):
    kind = key[0]
    if kind == "attr":
        return f"{key[2]}.self.{key[3]}"
    if kind == "global":
        return f"module global {key[2]!r}"
    return f"closure {key[3]!r} of {key[2]}()"


def _root_display(proj, key):
    if key == conc.MAIN_ROOT:
        return "main"
    r = proj.root_by_key(key)
    if r is None:
        return key
    label = r.name or f"{r.kind}@{r.path}:{r.node.lineno}"
    return label + ("[xN]" if r.multi else "")


def _hot(mm, fm):
    if mm.path.endswith(_HOT_MODULES):
        return True
    return any(d and (d == "hot_path" or d.endswith(".hot_path"))
               for d in fm.decorators)


def _blocking_marker(call, held):
    d = call.dotted
    if d and (d in _BLOCK_DOTTED or d.startswith(_BLOCK_PREFIXES)):
        return d
    if call.spec[0] == "name" and call.spec[1] == "open":
        return "open()"
    if call.attr in _BLOCK_ATTRS:
        return f".{call.attr}()"
    if call.attr == "wait" and call.recv_lock not in held:
        return ".wait()"
    return None


def _signal_unsafe(call):
    d = call.dotted
    if d in _SIGNAL_SAFE_DOTTED:
        return None
    if call.attr is not None:
        if call.attr in _SIGNAL_SAFE_ATTRS:
            return None
        if call.attr in ("acquire", "join", "wait", "write", "flush",
                         "put", "get", "print"):
            return f".{call.attr}()"
    if call.spec[0] == "name":
        if call.spec[1] in ("print", "open", "input"):
            return f"{call.spec[1]}()"
        return None  # helper call: resolved and walked via topology
    if d and d.startswith(("logging.", "subprocess.", "obs.", "jax.",
                           "sys.stdout", "sys.stderr")):
        return d
    if d == "open" or d in ("os.write", "os.system"):
        return d
    return None


def check_project(mods):
    findings = []
    by_path = {m.path: m for m in mods}
    proj = conc.build_project(mods)

    def emit(path, rule, node, message):
        mod = by_path.get(path)
        if mod is None:
            return
        findings.append(mod.finding(rule, RULES[rule].severity, node,
                                    message))

    _check_c101(proj, emit)
    _check_c201(proj, emit)
    _check_c301(proj, emit)
    _check_c401(proj, emit)
    _check_c5xx(proj, emit)
    return findings


def _check_c101(proj, emit):
    for key, writes in sorted(proj.shared_writes().items()):
        roots, weight = set(), 0
        for (fkey, w) in writes:
            for rk in proj.roots_of(*fkey):
                if rk.startswith("signal:"):
                    continue  # handlers interrupt, they don't race
                roots.add(rk)
        for rk in roots:
            if rk == conc.MAIN_ROOT:
                weight += 1
            else:
                r = proj.root_by_key(rk)
                weight += 2 if (r is not None and r.multi) else 1
        thread_roots = sorted(rk for rk in roots if rk != conc.MAIN_ROOT)
        if weight < 2 or not thread_roots:
            continue
        guard = None
        for (_, w) in writes:
            s = set(w.held)
            guard = s if guard is None else (guard & s)
        if guard:
            continue  # every write shares at least one lock
        ordered = sorted(writes, key=lambda fw: fw[1].node.lineno)
        anchor = next((w for (_, w) in ordered if not w.held),
                      ordered[0][1])
        names = ", ".join(_root_display(proj, rk)
                          for rk in sorted(roots))
        emit(key[1], "C101", anchor.node,
             f"{_display(key)} written from {len(roots)} thread "
             f"root(s) [{names}] with no consistent lock — guard every "
             f"write with one lock or confine writes to one thread")


def _check_c201(proj, emit):
    for cyc in proj.cycles():
        in_cyc = set(cyc)
        pairs = sorted(p for p in proj.edges
                       if p[0] in in_cyc and p[1] in in_cyc)
        if not pairs:
            continue
        path, node = proj.edges[pairs[0]]
        chain = " -> ".join(cyc + [cyc[0]])
        emit(path, "C201", node,
             f"lock-order inversion cycle: {chain} — threads taking "
             f"these locks in different orders can deadlock; pick one "
             f"global order")


def _check_c301(proj, emit):
    for mm in proj.mods.values():
        for fm in mm.funcs.values():
            if not _hot(mm, fm):
                continue
            for c in sorted(fm.calls, key=lambda c: c.node.lineno):
                if not c.held:
                    continue
                marker = _blocking_marker(c, c.held)
                if marker:
                    emit(mm.path, "C301", c.node,
                         f"blocking call {marker} while holding "
                         f"hot-path lock {c.held[-1]} — release before "
                         f"blocking or move the work off the lock")


def _check_c401(proj, emit):
    for mm in proj.mods.values():
        seen = set()
        for spec, handler_node, node in mm.signal_handlers:
            for fkey in proj.resolve_call(mm, spec):
                if fkey in seen:
                    continue
                seen.add(fkey)
                fm = proj.mods[fkey[0]].funcs[fkey[1]]
                if fm.direct_locks:
                    emit(mm.path, "C401", fm.node,
                         f"signal handler {fm.qualname}() acquires a "
                         f"lock — handlers interrupt the lock's owner")
                    continue
                for c in sorted(fm.calls, key=lambda c: c.node.lineno):
                    what = _signal_unsafe(c)
                    if what:
                        emit(fkey[0], "C401", c.node,
                             f"signal handler {fm.qualname}() calls "
                             f"{what} — not async-signal-safe; set a "
                             f"flag/Event and do the work outside")
                        break


def _check_c5xx(proj, emit):
    for mm in proj.mods.values():
        threaded = mm.has_threads
        for fm in mm.funcs.values():
            for c in sorted(fm.calls, key=lambda c: c.node.lineno):
                d = c.dotted
                if d == "os.fork" and threaded:
                    emit(mm.path, "C501", c.node,
                         "os.fork() after threads started: the child "
                         "inherits locked locks with no owner thread")
                elif (d and d.startswith("multiprocessing.")
                        and d.rsplit(".", 1)[-1] in _MP_SPAWNERS
                        and threaded):
                    emit(mm.path, "C502", c.node,
                         f"{d} in a thread-starting module: the fork "
                         f"start method snapshots foreign locks "
                         f"mid-flight — use the spawn context")
                elif d and d.startswith("subprocess.") and any(
                        kw.arg == "preexec_fn" for kw in c.node.keywords):
                    emit(mm.path, "C503", c.node,
                         "preexec_fn runs between fork and exec where "
                         "only async-signal-safe code is legal — use "
                         "process_group/env arguments instead")
