"""The ``lint`` and ``audit`` CLI verbs (``__main__.py``):

    python -m flake16_framework_tpu lint [PATHS...] [--json]
        [--baseline FILE] [--telemetry PATH] [--rules] [--ir]
        [--concurrency]
    python -m flake16_framework_tpu audit [--json] [--budget-mb MB]
        [--n N] [--trees T] [--max-depth D] [--no-mesh]

With no PATHS the package itself is linted (the CI gate invocation —
tests/test_lint.py shells exactly this and asserts exit 0). ``--json``
prints the ``lint-report-v1`` document (obs.schema.LINT_SCHEMA — same
schema family as telemetry, validated by the same drift lint).
``--baseline`` subtracts a recorded fingerprint multiset
(tools/gen_lint_baseline.py writes one). ``--telemetry`` additionally
validates emitted telemetry documents at PATH (repeatable — the folded-in
tools/check_telemetry_schema.py behavior). ``--rules`` prints the rule
catalog and exits 0. ``--ir`` folds the f16audit IR findings into the
lint run (imports jax — the one lint path that does). ``--concurrency``
restricts the run to the f16race pack (C101–C503, rules_conc) — the
focused invocation for auditing the threaded serving substrate.

``audit`` is the standalone f16audit gate: trace every real entry point
(planner family programs, serve AOT executables, both SHAP kernels) and
run the I-rule pack — dispatch census reconciliation, host-callback and
determinism proofs, per-plan memory envelopes, shard_map sharding audit.
Exit 0 = every contract holds; findings print in lint format.

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = usage error
(mirroring the ValueError convention of the other verbs).
"""

import json
import os
import sys

from flake16_framework_tpu.analysis import engine as eng
from flake16_framework_tpu.analysis import (
    rules_conc, rules_grid, rules_ir, rules_jax, rules_obs,
)

# rules_ir registers its catalog only (no check_* hooks): plain lint
# stays jax-free; I-findings come from run_audit via ``audit``/``--ir``.
# rules_conc (f16race, C101–C503) runs in every lint — pure AST like the
# rest, dogfooded to zero on the package.
PACKS = (rules_jax, rules_grid, rules_obs, rules_ir, rules_conc)


def default_paths():
    """The package directory — what the CI gate lints."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def build_engine(packs=None):
    return eng.Engine(PACKS if packs is None else packs)


def run_lint(paths=None, baseline_file=None, telemetry_paths=(),
             ir=False, packs=None):
    """(LintResult, telemetry-doc findings folded in) for PATHS. With
    ``ir`` the f16audit IR findings join the result (imports jax).
    ``packs`` restricts the run (the ``--concurrency`` focus flag)."""
    engine = build_engine(packs)
    result = engine.lint(paths or default_paths(),
                         baseline=eng.load_baseline(baseline_file,
                                                    rules=engine.rules))
    if telemetry_paths:
        result.findings.extend(rules_obs.check_docs(telemetry_paths))
    if ir:
        ir_findings, _info = rules_ir.run_audit()
        result.findings.extend(ir_findings)
    return result


def lint_main(args, out=None):
    out = out or sys.stdout
    as_json = False
    show_rules = False
    with_ir = False
    conc_only = False
    baseline = None
    telemetry = []
    paths = []
    it = iter(args)
    for a in it:
        if a == "--json":
            as_json = True
        elif a == "--rules":
            show_rules = True
        elif a == "--ir":
            with_ir = True
        elif a == "--concurrency":
            conc_only = True
        elif a == "--baseline":
            baseline = next(it, None)
            if baseline is None:
                raise ValueError("--baseline needs a file argument")
        elif a == "--telemetry":
            t = next(it, None)
            if t is None:
                raise ValueError("--telemetry needs a path argument")
            telemetry.append(t)
        elif a.startswith("--"):
            raise ValueError(f"Unrecognized lint option {a!r}")
        else:
            paths.append(a)

    packs = (rules_conc,) if conc_only else None
    if show_rules:
        engine = build_engine(packs)
        for r in sorted(engine.rules.values(), key=lambda r: r.id):
            out.write(f"{r.id:<6}{r.severity:<9}{r.doc}\n")
        return 0

    result = run_lint(paths, baseline_file=baseline,
                      telemetry_paths=telemetry, ir=with_ir, packs=packs)
    report = result.to_report()
    if as_json:
        out.write(json.dumps(report, indent=1) + "\n")
    else:
        for f in result.findings:
            out.write(f.render() + "\n")
        c = report["counts"]
        out.write(
            f"{c['errors']} error(s), {c['warnings']} warning(s) over "
            f"{c['files']} file(s); suppressed: {c['suppressed_inline']} "
            f"inline, {c['suppressed_baseline']} baseline\n")
    return 1 if result.findings else 0


def audit_report(findings, info):
    """The ``audit-report-v1`` document (obs.schema.AUDIT_SCHEMA)."""
    from flake16_framework_tpu.obs import schema

    errors = [f for f in findings if f.severity == eng.ERROR]
    return {
        "schema": schema.AUDIT_SCHEMA,
        "findings": [f.as_dict() for f in findings],
        "counts": {"errors": len(errors),
                   "warnings": len(findings) - len(errors),
                   "entries": len(info["entries"])},
        "census": info["census"],
        "shap_census": info.get("shap_census"),
        "envelopes": info["envelopes"],
        "entries": info["entries"],
        "budget_mb": info["budget_mb"],
    }


def audit_main(args, out=None):
    out = out or sys.stdout
    as_json = False
    kw = {}
    it = iter(args)

    def arg(flag):
        v = next(it, None)
        if v is None:
            raise ValueError(f"{flag} needs an argument")
        return v

    for a in it:
        if a == "--json":
            as_json = True
        elif a == "--budget-mb":
            kw["budget_mb"] = float(arg(a))
        elif a == "--n":
            kw["n"] = int(arg(a))
        elif a == "--trees":
            kw["n_trees"] = int(arg(a))
        elif a == "--max-depth":
            kw["max_depth"] = int(arg(a))
        elif a == "--no-mesh":
            kw["mesh"] = False
        else:
            raise ValueError(f"Unrecognized audit option {a!r}")

    findings, info = rules_ir.run_audit(**kw)
    if as_json:
        out.write(json.dumps(audit_report(findings, info), indent=1)
                  + "\n")
    else:
        for f in findings:
            out.write(f.render() + "\n")
        c = info["census"]
        sc = info.get("shap_census") or {}
        out.write(
            f"audit: {len(info['entries'])} entr(ies) traced; census "
            f"static={c['static']} runtime={c['runtime']} "
            f"({c['source']}); shap census "
            f"static={sc.get('static')} runtime={sc.get('runtime')} "
            f"({sc.get('source')}); {len(findings)} finding(s)\n")
        for env in info["envelopes"]:
            out.write(
                f"  {env['entry']:<44} batch={env['batch']:<4} "
                f"peak={env['peak_mb']:.2f} MB\n")
    return 1 if findings else 0
