"""The ``lint`` CLI verb (``__main__.py``), mirroring ``report``:

    python -m flake16_framework_tpu lint [PATHS...] [--json]
        [--baseline FILE] [--telemetry PATH] [--rules]

With no PATHS the package itself is linted (the CI gate invocation —
tests/test_lint.py shells exactly this and asserts exit 0). ``--json``
prints the ``lint-report-v1`` document (obs.schema.LINT_SCHEMA — same
schema family as telemetry, validated by the same drift lint).
``--baseline`` subtracts a recorded fingerprint multiset
(tools/gen_lint_baseline.py writes one). ``--telemetry`` additionally
validates emitted telemetry documents at PATH (repeatable — the folded-in
tools/check_telemetry_schema.py behavior). ``--rules`` prints the rule
catalog and exits 0.

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = usage error
(mirroring the ValueError convention of the other verbs).
"""

import json
import os
import sys

from flake16_framework_tpu.analysis import engine as eng
from flake16_framework_tpu.analysis import rules_grid, rules_jax, rules_obs

PACKS = (rules_jax, rules_grid, rules_obs)


def default_paths():
    """The package directory — what the CI gate lints."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def build_engine():
    return eng.Engine(PACKS)


def run_lint(paths=None, baseline_file=None, telemetry_paths=()):
    """(LintResult, telemetry-doc findings folded in) for PATHS."""
    engine = build_engine()
    result = engine.lint(paths or default_paths(),
                         baseline=eng.load_baseline(baseline_file))
    if telemetry_paths:
        result.findings.extend(rules_obs.check_docs(telemetry_paths))
    return result


def lint_main(args, out=None):
    out = out or sys.stdout
    as_json = False
    show_rules = False
    baseline = None
    telemetry = []
    paths = []
    it = iter(args)
    for a in it:
        if a == "--json":
            as_json = True
        elif a == "--rules":
            show_rules = True
        elif a == "--baseline":
            baseline = next(it, None)
            if baseline is None:
                raise ValueError("--baseline needs a file argument")
        elif a == "--telemetry":
            t = next(it, None)
            if t is None:
                raise ValueError("--telemetry needs a path argument")
            telemetry.append(t)
        elif a.startswith("--"):
            raise ValueError(f"Unrecognized lint option {a!r}")
        else:
            paths.append(a)

    if show_rules:
        engine = build_engine()
        for r in sorted(engine.rules.values(), key=lambda r: r.id):
            out.write(f"{r.id:<6}{r.severity:<9}{r.doc}\n")
        return 0

    result = run_lint(paths, baseline_file=baseline,
                      telemetry_paths=telemetry)
    report = result.to_report()
    if as_json:
        out.write(json.dumps(report, indent=1) + "\n")
    else:
        for f in result.findings:
            out.write(f.render() + "\n")
        c = report["counts"]
        out.write(
            f"{c['errors']} error(s), {c['warnings']} warning(s) over "
            f"{c['files']} file(s); suppressed: {c['suppressed_inline']} "
            f"inline, {c['suppressed_baseline']} baseline\n")
    return 1 if result.findings else 0
