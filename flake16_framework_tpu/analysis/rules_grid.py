"""Rule pack 2 — grid pre-flight (G-rules).

Statically validate the full config grid (the paper's 2x2x3x6x3 = 216,
but derived from config.py axes, NOT pinned — ROADMAP item 4 adds model
families, and the pre-flight must not fight it) against the implemented
kernel registry BEFORE a multi-hour TPU run: a malformed config axis
must fail in seconds on the host, not hours into an allocation (ISSUE 2
acceptance: reject a broken grid in <5s without touching a device —
nothing in this module imports jax).

Checks, each its own rule id:

- G101 grid-shape: five non-empty dict axes; the axes multiply out to
  the same count ``config.iter_config_keys()`` enumerates (the default
  expected size derives from the enumeration, so adding an axis value
  in config.py moves BOTH sides together and a *skew* between the axes
  and the enumeration is what actually fires).
- G102 kernel-registry: preprocessing/balancing codes are EXACTLY
  ``range(len(axis))`` — they index ``lax.switch`` branch tuples, so a
  gap or duplicate silently runs the wrong kernel (worse than a crash);
  the static branch-tuple arity in ops/preprocess.py and ops/resample.py
  must match the axis size; every (prep, bal, model) triple resolves.
- G103 static-hashability: model specs and feature-set column tuples
  must be hashable — they key the per-family compile caches in
  parallel/sweep.py (``_get_fns``); an unhashable spec retraces per
  config instead of once per family.
- G104 padded-shapes: feature columns are unique ints inside
  ``range(n_features)`` (column indexing into the padded [N, F] matrix).
- G105 span-collision: a telemetry span name declared in two different
  modules merges unrelated timings in ``report`` — span names must be
  unique per module (the sweep/pipeline naming contract, obs/report.py).
- G106 knob-census: every ``F16_*`` env read in the package must be
  declared in the ``KNOBS`` registry below (name + value validator), a
  declared knob must still be read somewhere (stale entries rot into
  folklore), and any knob SET in the current environment must hold a
  value its validator accepts — a typo'd grower A/B arm
  (``F16_ENSEMBLE_GROWER=hsit``) fails the pre-flight in seconds on the
  host instead of silently running the wrong tier for hours (the ISSUE-9
  grower knobs are exactly such model-changing switches).
- G107 executor-scope dispatch loop (per-module, ISSUE 12): a Python
  ``for``/``while`` loop that calls ``run_config`` inside a function
  marked ``@executor_scope`` (parallel/sweep.py) re-introduces the
  per-config dispatch round-trip the planner/executor exists to delete
  — the exact anti-pattern behind the BENCH_r07 regression (one
  dispatch per config x fold instead of one program per family plan).
  Executor-scope functions must dispatch BATCHES; per-config fallback
  belongs outside the scope (run_grid's guard-salvage tier).
- G108 tunable-constant census (per-module, ISSUE 20): a module-level
  ALL-CAPS integer literal whose name carries a tunable suffix (BATCH,
  CHUNK, BLK, TILE, BINS, WINDOW, WIDTH) in a jax-importing module is a
  hardcoded kernel tunable the f16tune autotuner cannot see. Register
  the matching ``F16_<NAME>`` knob in the KnobSpace (perf/tuner.py
  KNOBSPACE) and derive the constant from its env read — PROFILE.md's
  ledger shows these optima flip with shape, so a frozen literal is
  wall-clock left on the table that no search will ever reclaim.

``preflight_grid`` is callable with injected axes so tests (and future
config loaders) can validate a candidate grid without editing config.py.
"""

import ast
import os

from flake16_framework_tpu.analysis.engine import (
    ERROR, WARNING, Finding, RuleInfo, normpath,
)

RULES = {r.id: r for r in (
    RuleInfo("G101", ERROR, "grid axes malformed or config count drifted"),
    RuleInfo("G102", ERROR,
             "axis code does not resolve to a real kernel (lax.switch"
             " registry mismatch)"),
    RuleInfo("G103", ERROR,
             "static spec unhashable — defeats the per-family compile"
             " cache (retrace per config)"),
    RuleInfo("G104", ERROR, "feature columns out of range or duplicated"),
    RuleInfo("G105", WARNING,
             "telemetry span name declared in more than one module"),
    RuleInfo("G106", ERROR,
             "env knob census: undeclared F16_* read, stale registry"
             " entry, or invalid knob value in the current environment"),
    RuleInfo("G107", WARNING,
             "per-config dispatch loop inside @executor_scope — the"
             " planner/executor's whole-plan program replaced this"),
    RuleInfo("G108", WARNING,
             "tunable kernel constant hardcoded without a KnobSpace"
             " registration — f16tune cannot search what it cannot see"),
)}

# The declared F16_* knob registry (G106): name -> (kind, detail).
# kind "enum": detail is the allowed value tuple; "int"/"float": detail is
# the inclusive minimum; "str": free-form (censused but not value-checked).
# Model-CHANGING knobs (grower tier, ET draw, refinement, bins) sit next
# to pure perf knobs here on purpose: the census is the one place a
# reviewer sees every behavior switch the package reads.
KNOBS = {
    "F16_TELEMETRY": ("str", None),
    "F16_TELEMETRY_HEARTBEAT_S": ("float", 0.0),
    "F16_FAULT_INJECT": ("str", None),
    "F16_FAULT_MAX_ATTEMPTS": ("int", 1),
    "F16_FAULT_BACKOFF_S": ("float", 0.0),
    "F16_FAULT_BACKOFF_MAX_S": ("float", 0.0),
    "F16_FAULT_ENVELOPE_S": ("float", 0.0),
    "F16_PCA_IMPL": ("enum", ("", "svd", "eigh")),
    "F16_SHAP_SBLK": ("int", 1),
    "F16_SHAP_LBLK": ("int", 1),
    # work-item SHAP engine knobs (ops/treeshap.py, ISSUE 14): path-block
    # width of the packed unit kernel, and the live-read explain
    # tree-chunk bound (consulted per call through the resilience
    # ladder's halving path — not frozen at import).
    "F16_SHAP_PBLK": ("int", 1),
    "F16_SHAP_TREE_CHUNK": ("int", 1),
    # grower tier + histogram-grower knobs (ops/trees.py, ISSUE 9)
    "F16_ENSEMBLE_GROWER": ("enum", ("hist", "exact")),
    "F16_HIST_BINS": ("int", 2),
    "F16_HIST_NODE_BATCH": ("int", 1),
    "F16_HIST_NODE_BATCH_CPU": ("int", 0),
    "F16_HIST_IMPL": ("enum", ("auto", "xla", "einsum", "pallas",
                               "segsum")),
    "F16_HIST_REFINE": ("enum", ("exact", "edge")),
    # f16tune-searchable exact-split refinement tile (ops/trees.py,
    # ISSUE 20): 0 = one-shot masked reduce; a positive tile streams the
    # [N, W] max/min in bitwise-identical chunks to shrink the live set.
    "F16_HIST_REFINE_TILE": ("int", 0),
    "F16_ET_DRAW": ("enum", ("value", "rank")),
    "F16_FEATURE_QUOTA": ("enum", ("sklearn", "informative")),
    "F16_PREDICT_WINDOW": ("int", 1),
    "F16_PREDICT_IMPL": ("enum", ("gather", "windows")),
    # f16audit device budget (ISSUE 13): when set (MB), the sweep's plan
    # pre-flight refuses any family program whose peak-memory envelope
    # exceeds it (parallel/sweep._preflight_plan_budget, I401).
    "F16_DEVICE_BUDGET_MB": ("float", 0.0),
    # observability plane (ISSUE 15): per-request trace sampling rate
    # (obs/core.mint_trace; 0 disables, 1 samples every request), the
    # jax.profiler capture directory for the plan/serve dispatch hooks
    # (obs/core.xprof_trace), and the crash-surviving flight-ring arming
    # path (obs/flight.py; "1" = <run_dir>/flight.bin).
    "F16_TRACE_SAMPLE": ("float", 0.0),
    "F16_XPROF": ("str", None),
    "F16_FLIGHT": ("str", None),
    # Performance-observatory database path (obs/perfdb.py): a file
    # path, "" for the _scratch default, "0" disables the consult.
    "F16_PERFDB": ("str", None),
    # The f16race runtime lock-order witness (obs/lockwatch.py): "1"
    # arms the tracer and dumps lockwatch.json to the CWD at exit; any
    # other non-empty value is the dump path; ""/"0" leaves it off.
    "F16_LOCKWATCH": ("str", None),
    # Serving fleet (ISSUE 18). F16_FLEET_WORKER: set by the fleet
    # supervisor in each worker's env to its index — consumed by
    # serve/fleet.py (worker identity) and obs/flight.py (per-worker
    # ring-path uniquification); never set by hand. The rest tune the
    # router: hedge delay before a second dispatch of a slow request,
    # worker heartbeat period, and the heartbeat-staleness bound past
    # which a worker is routed around as stalled.
    "F16_FLEET_WORKER": ("str", None),
    "F16_FLEET_HEDGE_MS": ("float", 0.0),
    "F16_FLEET_HEARTBEAT_S": ("float", 0.0),
    "F16_FLEET_STALL_S": ("float", 0.0),
}

# The PAPER's grid size — historical reference only. The pre-flight's
# default expectation is derived from config.iter_config_keys() (see
# default_grid_size), so growing the grid (ROADMAP item 4) needs no edit
# here; tests that want the paper's exact grid pass expected_size=216.
PAPER_GRID_SIZE = 216


def default_grid_size():
    """The config count the package's own enumeration yields — what the
    planner, the sweep, and the audit census all iterate. Deriving the
    G101 expectation from it (instead of pinning 216) turns the check
    into axes-vs-enumeration consistency."""
    from flake16_framework_tpu import config as cfg

    return len(list(cfg.iter_config_keys()))


def _finding(rule_id, message, path="flake16_framework_tpu/config.py",
             line=0):
    return Finding(rule_id, RULES[rule_id].severity, path, line, 0,
                   message, snippet=message)


def _switch_arity(path):
    """Largest ``lax.switch(code, (branches...))`` branch-tuple arity in a
    file, by AST (None when the file has no literal-tuple switch). This is
    the *implemented* kernel count the config axis must match."""
    try:
        with open(path, encoding="utf-8") as fd:
            tree = ast.parse(fd.read())
    except (OSError, SyntaxError):
        return None
    best = None
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "switch"
                and len(node.args) >= 2
                and isinstance(node.args[1], (ast.Tuple, ast.List))):
            arity = len(node.args[1].elts)
            best = arity if best is None else max(best, arity)
    return best


def default_switch_arities():
    """The implemented kernel counts, read off the ops sources."""
    ops = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "ops")
    return {
        "preprocessing": _switch_arity(os.path.join(ops, "preprocess.py")),
        "balancing": _switch_arity(os.path.join(ops, "resample.py")),
    }


def preflight_grid(axes=None, *, n_features=None, expected_size=None,
                   switch_arities=None):
    """Validate a candidate grid (default: the real config.GRID_AXES)
    against the kernel registry. Returns a list of Findings — empty means
    the grid is launchable. Pure host-side; never imports jax."""
    if axes is None:
        from flake16_framework_tpu import config as cfg

        axes = cfg.GRID_AXES
        expected_size = (default_grid_size() if expected_size is None
                         else expected_size)
    if n_features is None:
        from flake16_framework_tpu.constants import N_FEATURES

        n_features = N_FEATURES
    if switch_arities is None:
        switch_arities = default_switch_arities()

    findings = []

    # G101: shape of the grid itself.
    if len(axes) != 5:
        findings.append(_finding(
            "G101", f"grid has {len(axes)} axes, want 5 "
            "(flaky, feature-set, preprocessing, balancing, model)"))
        return findings
    names = ("flaky", "feature-set", "preprocessing", "balancing", "model")
    size = 1
    for name, axis in zip(names, axes):
        if not isinstance(axis, dict) or not axis:
            findings.append(_finding(
                "G101", f"{name} axis is not a non-empty dict"))
            return findings
        size *= len(axis)
    if expected_size is not None and size != expected_size:
        findings.append(_finding(
            "G101", f"grid multiplies out to {size} configs, "
            f"want {expected_size}"))

    flaky, feature_sets, preps, bals, models = axes

    # G102: switch-indexed axes must be exactly range(len(axis)).
    for name, axis in (("preprocessing", preps), ("balancing", bals)):
        codes = sorted(v for v in axis.values() if isinstance(v, int))
        if len(codes) != len(axis) or codes != list(range(len(axis))):
            findings.append(_finding(
                "G102", f"{name} codes {sorted(axis.values())!r} are not "
                f"exactly range({len(axis)}) — lax.switch would clamp or "
                "run the wrong kernel"))
        arity = switch_arities.get(name)
        if arity is not None and arity != len(axis):
            findings.append(_finding(
                "G102", f"{name} axis has {len(axis)} settings but the "
                f"implemented lax.switch dispatches {arity} kernels"))
    for name, label in flaky.items():
        if not isinstance(label, int):
            findings.append(_finding(
                "G102", f"flaky type {name!r} label {label!r} is not an "
                "int class label"))

    # G102/G103: every model resolves to a fit-able static spec.
    for name, spec in models.items():
        n_trees = getattr(spec, "n_trees", None)
        if not isinstance(n_trees, int) or n_trees < 1:
            findings.append(_finding(
                "G102", f"model {name!r} has no positive int n_trees "
                f"({n_trees!r}) — no fused/staged fit path exists for it"))
        try:
            hash(spec)
        except TypeError:
            findings.append(_finding(
                "G103", f"model spec {name!r} is unhashable — it keys the "
                "per-family jit cache (sweep._get_fns)"))

    # G103/G104: feature sets are hashable tuples of in-range columns.
    for name, cols in feature_sets.items():
        try:
            hash(cols)
        except TypeError:
            findings.append(_finding(
                "G103", f"feature set {name!r} columns are unhashable "
                f"({type(cols).__name__}) — must be a tuple"))
        cols_list = list(cols)
        if not cols_list:
            findings.append(_finding(
                "G104", f"feature set {name!r} is empty"))
            continue
        bad = [c for c in cols_list
               if not isinstance(c, int) or not 0 <= c < n_features]
        if bad:
            findings.append(_finding(
                "G104", f"feature set {name!r} columns {bad!r} outside "
                f"range({n_features})"))
        if len(set(cols_list)) != len(cols_list):
            findings.append(_finding(
                "G104", f"feature set {name!r} has duplicate columns"))
    return findings


def _knob_reads(mod):
    """(knob, lineno) for every literal ``F16_*`` environment read in a
    module: ``<env>.get/setdefault/pop("F16_X", ...)`` and
    ``<env>["F16_X"]`` forms (the resilience policies take an injected
    ``environ`` mapping, so ANY receiver counts, not just ``os.environ``
    — a knob string is the census key either way). A name bound to a
    knob literal (``ENV_VAR = "F16_FAULT_INJECT"``) counts as that
    knob's read site: the binding exists to be .get()-ed."""
    out = []
    for node in ast.walk(mod.tree):
        const = None
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
                and node.value.value.startswith("F16_")):
            const = node.value
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "setdefault", "pop")
                and node.args):
            const = node.args[0]
        elif isinstance(node, ast.Subscript):
            const = node.slice
        if (isinstance(const, ast.Constant)
                and isinstance(const.value, str)
                and const.value.startswith("F16_")):
            out.append((const.value, node.lineno))
    return out


def _knob_value_ok(kind, detail, raw):
    if kind == "enum":
        return raw in detail
    if kind in ("int", "float"):
        try:
            val = int(raw) if kind == "int" else float(raw)
        except ValueError:
            return False
        return val >= detail
    return True


def preflight_knob_values(environ=None):
    """Validate every SET registry knob's value in ``environ`` (default
    the real environment) — the grower-knob pre-flight half of G106.
    Returns Findings; empty means the environment is launchable."""
    env = os.environ if environ is None else environ
    findings = []
    for name, (kind, detail) in sorted(KNOBS.items()):
        raw = env.get(name)
        if raw is None or _knob_value_ok(kind, detail, raw):
            continue
        want = ("|".join(v for v in detail if v) if kind == "enum"
                else f"{kind} >= {detail}")
        findings.append(_finding(
            "G106", f"env knob {name}={raw!r} is invalid (want {want}) — "
            "the run would crash at import or silently run a wrong arm",
            path="flake16_framework_tpu/analysis/rules_grid.py"))
    return findings


def _is_executor_scope(fn, aliases):
    """True when ``fn`` carries the ``@executor_scope`` marker
    (parallel/sweep.py), under any import alias."""
    from flake16_framework_tpu.analysis.rules_jax import _dotted

    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        dotted = _dotted(target, aliases)
        if dotted and (dotted == "executor_scope"
                       or dotted.endswith(".executor_scope")):
            return True
    return False


# G108: name suffixes that mark a module-level integer as a kernel
# tunable — the knob families the f16tune KnobSpace searches (batch and
# chunk widths, block/tile sizes, bin counts, window widths).
_TUNABLE_SUFFIXES = ("BATCH", "CHUNK", "BLK", "TILE", "BINS", "WINDOW",
                     "WIDTH")


def _imports_jax(tree):
    """True when the module imports jax (any form) — the G108 marker for
    'this file sits on a kernel path'."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "jax" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "jax":
                return True
    return False


def _registered_knob_envs():
    """The KnobSpace env-name accept-set (perf/tuner.py) — a constant
    whose ``F16_<NAME>`` counterpart is registered there is tunable by
    f16tune and exempt from G108. The tuner module is deliberately
    jax-free, so this import keeps the pre-flight off the device."""
    from flake16_framework_tpu.perf.tuner import registered_env_names

    return registered_env_names()


def check_tunable_constants(mod):
    """G108: module-level ``NAME = <int literal>`` with a tunable suffix
    in a jax-importing module. A bare literal is invisible to the
    autotuner; registered knobs are read via ``os.environ.get("F16_…")``
    (a Call, not a Constant), so the literal form itself is the tell."""
    if mod.tree is None or not _imports_jax(mod.tree):
        return []
    registered = _registered_knob_envs()
    findings = []
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and type(node.value.value) is int):
            continue
        for target in node.targets:
            if not isinstance(target, ast.Name):
                continue
            name = target.id.lstrip("_")
            if not (name.isupper()
                    and name.split("_")[-1] in _TUNABLE_SUFFIXES):
                continue
            if "F16_" + name in registered:
                continue  # KnobSpace owns it; the literal is a default
            findings.append(Finding(
                "G108", RULES["G108"].severity, normpath(mod.path),
                node.lineno, node.col_offset,
                f"kernel tunable {target.id} = {node.value.value} is a "
                "hardcoded literal with no KnobSpace registration — "
                "register F16_" + name + " in perf/tuner.py KNOBSPACE "
                "and derive the value from its env read so f16tune can "
                "search it (shape-dependent optima, PROFILE.md ledger)",
                snippet=target.id))
    return findings


def check_module(mod):
    """G107: per-config Python-loop device dispatch inside executor
    scope. ``@executor_scope`` (parallel/sweep.py) marks the functions
    whose contract is batched whole-plan dispatch; a ``run_config`` call
    under a ``for``/``while`` in one of them is the per-config
    round-trip anti-pattern this scope exists to exclude.

    G108: hardcoded tunable constants (check_tunable_constants)."""
    from flake16_framework_tpu.analysis.rules_jax import _import_aliases

    if mod.tree is None:
        return []
    aliases = _import_aliases(mod.tree)
    findings = list(check_tunable_constants(mod))
    seen = set()
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _is_executor_scope(fn, aliases):
            continue
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for call in ast.walk(loop):
                if not (isinstance(call, ast.Call)
                        and (call.func.attr if isinstance(
                            call.func, ast.Attribute)
                            else call.func.id if isinstance(
                                call.func, ast.Name) else None)
                        == "run_config"):
                    continue
                site = (call.lineno, call.col_offset)
                if site in seen:  # nested loops re-walk inner calls
                    continue
                seen.add(site)
                findings.append(Finding(
                    "G107", RULES["G107"].severity, normpath(mod.path),
                    call.lineno, call.col_offset,
                    f"run_config called in a loop inside @executor_scope "
                    f"function {fn.name!r} — one device round-trip per "
                    "config is the engine tax the planner deletes; "
                    "dispatch the whole plan (run_plan) or move the "
                    "per-config fallback outside executor scope",
                    snippet="run_config"))
    return findings


def _span_names(mod):
    """(name, lineno) for every literal obs.span("name", ...) in a module."""
    out = []
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "span"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.append((node.args[0].value, node.lineno))
    return out


def check_project(modules):
    """Grid pre-flight + span uniqueness + knob census, once per run."""
    findings = list(preflight_grid())
    findings.extend(preflight_knob_values())

    reads = {}
    for mod in modules:
        if ("/tests/" in f"/{mod.path}" or mod.path.startswith("tests/")
                or mod.tree is None):
            continue  # test fixtures may read ad-hoc knobs
        for name, lineno in _knob_reads(mod):
            reads.setdefault(name, []).append((mod.path, lineno))
    for name, sites in sorted(reads.items()):
        if name not in KNOBS:
            path, lineno = sites[0]
            findings.append(Finding(
                "G106", RULES["G106"].severity, normpath(path), lineno, 0,
                f"env knob {name!r} is read here but not declared in the "
                "G106 registry (analysis/rules_grid.py KNOBS) — declare "
                "it with a validator so the pre-flight can vet its value",
                snippet=name))
    # Stale-entry check only when the knob-bearing core is in the linted
    # set (single-file invocations would otherwise flag every entry).
    if any(mod.path.endswith("ops/trees.py") for mod in modules):
        for name in sorted(set(KNOBS) - set(reads)):
            findings.append(_finding(
                "G106", f"registry knob {name!r} is declared but never "
                "read in the package — stale entry (drop it or wire it)",
                path="flake16_framework_tpu/analysis/rules_grid.py"))

    owners = {}
    for mod in modules:
        if "/tests/" in f"/{mod.path}" or mod.path.startswith("tests/"):
            continue  # test fixtures may reuse production span names
        for name, lineno in _span_names(mod):
            owners.setdefault(name, []).append((mod.path, lineno))
    for name, sites in sorted(owners.items()):
        paths = sorted({p for p, _ in sites})
        if len(paths) > 1:
            path, lineno = sites[-1]
            findings.append(Finding(
                "G105", RULES["G105"].severity, normpath(path), lineno, 0,
                f"span name {name!r} declared in {len(paths)} modules "
                f"({', '.join(paths)}) — report would merge their walls",
                snippet=name))
    return findings
