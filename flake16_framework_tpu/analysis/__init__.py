"""f16lint — AST-based JAX/TPU-hygiene static analysis + grid pre-flight.

The launch-time twin of the telemetry subsystem (obs/): catch host
syncs, retrace hazards, dtype drift, a malformed 216-config grid, and
telemetry schema drift on the HOST, in seconds, before a device is ever
touched (ISSUE 2; PROFILE.md "Static analysis" has the rule catalog).

    python -m flake16_framework_tpu lint [PATHS] [--json] [--baseline F]

Engine mechanics in engine.py; rule packs in rules_jax.py (J-rules),
rules_grid.py (G-rules), rules_obs.py (O-rules); CLI in cli.py. Nothing
here imports jax.
"""

from flake16_framework_tpu.analysis.engine import (  # noqa: F401
    Engine,
    Finding,
    LintResult,
    Module,
    RuleInfo,
    load_baseline,
    save_baseline,
)
