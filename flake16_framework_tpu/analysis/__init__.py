"""f16lint + f16audit — static analysis from source text down to traced IR.

The launch-time twin of the telemetry subsystem (obs/): catch host
syncs, retrace hazards, dtype drift, a malformed config grid, and
telemetry schema drift on the HOST, in seconds, before a device is ever
touched (ISSUE 2; PROFILE.md "Static analysis" has the rule catalog).

    python -m flake16_framework_tpu lint [PATHS] [--json] [--baseline F]
    python -m flake16_framework_tpu audit [--json] [--budget-mb MB]

Engine mechanics in engine.py; rule packs in rules_jax.py (J-rules),
rules_grid.py (G-rules), rules_obs.py (O-rules), rules_ir.py (I-rules —
the f16audit jaxpr-level pack, ISSUE 13); CLI in cli.py. Import
contract: nothing imports jax at module level — plain ``lint`` stays a
host-only pre-flight. The ONE exception is ir.py (the jaxpr
tracer/walkers), which imports jax by design and is therefore only
imported lazily, from inside the ``audit``/``lint --ir`` entry points
(tests/test_lint.py::test_analysis_never_imports_jax enforces this).
"""

from flake16_framework_tpu.analysis.engine import (  # noqa: F401
    Engine,
    Finding,
    LintResult,
    Module,
    RuleInfo,
    load_baseline,
    save_baseline,
)
