"""f16lint engine — AST static analysis, the host-side pre-flight twin of
the telemetry subsystem (obs/): telemetry explains a run after the fact,
f16lint refuses the classes of defect that burn a TPU allocation *before*
launch (ISSUE 2; PROFILE.md "Static analysis").

Mechanics, not rules, live here. A rule *pack* is a module exposing

    RULES : {rule_id: RuleInfo}            # the pack's catalog
    check_module(mod)  -> iter[Finding]    # optional, per parsed file
    check_project(mods) -> iter[Finding]   # optional, once per lint run

(the packs: rules_jax — TPU hygiene; rules_grid — 216-config grid
pre-flight; rules_obs — telemetry schema drift). The engine parses each
``.py`` once into a ``Module`` (source + AST + suppression table) and
funnels every pack's findings through the two suppression layers:

- inline: ``# f16lint: disable=J101,J402`` on the offending line (bare
  ``disable`` silences every rule on that line); ``disable-file=RULE``
  anywhere in the file silences a rule for the whole file.
- baseline: a JSON file of finding fingerprints (multiset — N entries
  absorb N findings). Fingerprints hash (path, rule, source snippet),
  not line numbers, so unrelated edits above a known finding don't
  invalidate the baseline. ``tools/gen_lint_baseline.py`` regenerates.

Nothing in this package imports jax: the grid pre-flight acceptance bar
is "reject a broken grid in seconds without touching a device", and an
import of jax is already a device backend negotiation.
"""

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass

ERROR, WARNING = "error", "warning"
# v2 groups fingerprints per rule pack so a pack added AFTER baseline
# generation cannot silently absorb findings it never saw (the
# gen_lint_baseline bug: a v1 flat list regenerated pre-I-rules would
# swallow later I-findings wholesale). v1 documents still load.
BASELINE_SCHEMA = "flake16-lint-baseline-v2"
BASELINE_SCHEMA_V1 = "flake16-lint-baseline-v1"

# Rule-id prefix letter -> pack section name in a v2 baseline. The
# fingerprint format (``RULE:hash``) keeps the rule id recoverable, so
# grouping needs no extra bookkeeping at save time.
PACK_PREFIXES = {"E": "engine", "J": "jax", "G": "grid", "O": "obs",
                 "I": "ir", "C": "concurrency"}


def pack_of(rule_id):
    pack = PACK_PREFIXES.get(rule_id[:1])
    if pack is None:
        raise ValueError(f"rule id {rule_id!r} matches no known pack "
                         f"(prefixes: {sorted(PACK_PREFIXES)})")
    return pack

# One engine-owned rule: a file the AST rules never saw is a finding, not
# a silent skip (a syntax error in a sweep module would otherwise pass).
PARSE_RULE = "E001"


@dataclass(frozen=True)
class RuleInfo:
    id: str
    severity: str
    doc: str


ENGINE_RULES = {
    PARSE_RULE: RuleInfo(PARSE_RULE, ERROR, "file does not parse"),
}


@dataclass
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""

    @property
    def fingerprint(self):
        """Stable identity for baselines: path + rule + source snippet
        (NOT the line number — edits above a finding must not churn the
        baseline)."""
        h = hashlib.sha1(
            f"{self.path}::{self.rule}::{self.snippet.strip()}".encode()
        ).hexdigest()[:16]
        return f"{self.rule}:{h}"

    def as_dict(self):
        return {
            "rule": self.rule, "severity": self.severity, "path": self.path,
            "line": self.line, "col": self.col, "message": self.message,
            "fingerprint": self.fingerprint,
        }

    def render(self):
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity}] {self.message}")


_DISABLE_RE = re.compile(
    r"#\s*f16lint:\s*disable(?P<file>-file)?"
    r"(?:=(?P<rules>[A-Za-z0-9_,\s-]+))?")


class Module:
    """One parsed source file: AST + per-line/per-file suppression table.

    ``tree`` is None when the file does not parse; the engine turns that
    into a PARSE_RULE finding instead of running rules on it."""

    def __init__(self, path, src=None):
        self.path = normpath(path)
        if src is None:
            with open(path, encoding="utf-8", errors="replace") as fd:
                src = fd.read()
        self.src = src
        self.lines = src.splitlines()
        self.tree = None
        self.parse_error = None
        try:
            self.tree = ast.parse(src)
        except SyntaxError as e:
            self.parse_error = e
        self.file_disables = set()
        self.line_disables = {}
        for lineno, line in enumerate(self.lines, start=1):
            m = _DISABLE_RE.search(line)
            if not m:
                continue
            rules = m.group("rules")
            ids = ({r.strip() for r in rules.split(",") if r.strip()}
                   if rules else {"*"})
            if m.group("file"):
                self.file_disables |= ids
            else:
                self.line_disables.setdefault(lineno, set()).update(ids)

    def suppressed(self, rule, line):
        if "*" in self.file_disables or rule in self.file_disables:
            return True
        ids = self.line_disables.get(line)
        return ids is not None and ("*" in ids or rule in ids)

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule_id, severity, node, message):
        """Finding anchored at an AST node, snippet auto-filled."""
        line = getattr(node, "lineno", 0)
        return Finding(rule_id, severity, self.path, line,
                       getattr(node, "col_offset", 0), message,
                       snippet=self.line_text(line))


def normpath(path):
    """Repo-relative posix path when under the CWD (stable fingerprints
    across checkouts), absolute otherwise."""
    apath = os.path.abspath(path)
    cwd = os.getcwd()
    if apath == cwd or apath.startswith(cwd + os.sep):
        apath = os.path.relpath(apath, cwd)
    return apath.replace(os.sep, "/")


def iter_py_files(paths):
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py") or os.path.isfile(p):
            yield p


class LintResult:
    def __init__(self, findings, *, suppressed_inline, suppressed_baseline,
                 n_files, rules):
        self.findings = findings
        self.suppressed_inline = suppressed_inline
        self.suppressed_baseline = suppressed_baseline
        self.n_files = n_files
        self.rules = rules

    @property
    def errors(self):
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self):
        return [f for f in self.findings if f.severity == WARNING]

    def to_report(self):
        """The ``lint-report-v1`` document (obs.schema.LINT_SCHEMA — the
        same JSONL/JSON schema family as telemetry events and reports)."""
        from flake16_framework_tpu.obs import schema

        return {
            "schema": schema.LINT_SCHEMA,
            "findings": [f.as_dict() for f in self.findings],
            "counts": {
                "errors": len(self.errors),
                "warnings": len(self.warnings),
                "suppressed_inline": self.suppressed_inline,
                "suppressed_baseline": self.suppressed_baseline,
                "files": self.n_files,
            },
            "rules": {r.id: {"severity": r.severity, "doc": r.doc}
                      for r in sorted(self.rules.values(),
                                      key=lambda r: r.id)},
            # Additive (validate_lint_report is permissive on extras, so
            # flake16-lint-report-v1 consumers are unaffected): the pack
            # sections this run's catalog spans, baseline-v2 vocabulary.
            "packs": sorted({pack_of(rid) for rid in self.rules}),
        }


class Engine:
    """Run rule packs over paths; apply suppressions and baseline."""

    def __init__(self, packs):
        self.packs = list(packs)
        self.rules = dict(ENGINE_RULES)
        for p in self.packs:
            dup = set(self.rules) & set(p.RULES)
            if dup:
                raise ValueError(f"duplicate rule ids across packs: {dup}")
            self.rules.update(p.RULES)

    def parse(self, paths):
        return [Module(f) for f in iter_py_files(paths)]

    def lint(self, paths, baseline=None):
        modules = self.parse(paths)
        findings = []
        for mod in modules:
            if mod.tree is None:
                e = mod.parse_error
                findings.append(Finding(
                    PARSE_RULE, ERROR, mod.path, e.lineno or 0,
                    (e.offset or 1) - 1, f"syntax error: {e.msg}",
                    snippet=e.text or ""))
                continue
            for p in self.packs:
                check = getattr(p, "check_module", None)
                if check is not None:
                    findings.extend(check(mod))
        parsed = [m for m in modules if m.tree is not None]
        for p in self.packs:
            check = getattr(p, "check_project", None)
            if check is not None:
                findings.extend(check(parsed))

        by_path = {m.path: m for m in modules}
        kept, n_inline = [], 0
        for f in findings:
            mod = by_path.get(f.path)
            if mod is not None and mod.suppressed(f.rule, f.line):
                n_inline += 1
            else:
                kept.append(f)

        budget = {}
        for fp in (baseline or ()):
            budget[fp] = budget.get(fp, 0) + 1
        final, n_base = [], 0
        for f in kept:
            fp = f.fingerprint
            if budget.get(fp, 0) > 0:
                budget[fp] -= 1
                n_base += 1
            else:
                final.append(f)
        final.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return LintResult(
            final, suppressed_inline=n_inline, suppressed_baseline=n_base,
            n_files=len(modules), rules=self.rules)


def load_baseline(path, rules=None):
    """Fingerprint list from a baseline file (empty when absent: a fresh
    checkout with no baseline is not a lint failure). Reads both v2
    (per-pack sections) and legacy v1 (flat list). When ``rules`` — the
    engine's rule catalog — is given, any fingerprint whose rule id is
    unknown raises instead of silently suppressing nothing (a typo or a
    renamed rule in a baseline is stale suppression debt, not noise)."""
    if path is None or not os.path.exists(path):
        return []
    with open(path) as fd:
        obj = json.load(fd)
    schema = obj.get("schema") if isinstance(obj, dict) else None
    if schema == BASELINE_SCHEMA:
        packs = obj.get("packs", {})
        fps = [fp for pack in sorted(packs) for fp in packs[pack]]
    elif schema == BASELINE_SCHEMA_V1:
        fps = list(obj.get("fingerprints", []))
    else:
        raise ValueError(
            f"{path}: not a {BASELINE_SCHEMA} (or {BASELINE_SCHEMA_V1}) "
            "baseline document")
    if rules is not None:
        unknown = sorted({fp.split(":", 1)[0] for fp in fps}
                         - set(rules))
        if unknown:
            raise ValueError(
                f"{path}: baseline names rule id(s) unknown to the "
                f"catalog: {unknown} — regenerate with "
                "tools/gen_lint_baseline.py")
    return fps


def group_fingerprints(findings):
    """{pack: sorted fingerprint list} for a finding set — the v2
    baseline body."""
    packs = {}
    for f in findings:
        packs.setdefault(pack_of(f.rule), []).append(f.fingerprint)
    return {pack: sorted(fps) for pack, fps in sorted(packs.items())}


def save_baseline(path, findings, *, keep_packs=None):
    """Write a v2 baseline. ``keep_packs`` ({pack: [fingerprints]})
    carries sections to preserve verbatim — the per-pack regeneration
    path: packs regenerated from ``findings`` override, others survive
    untouched."""
    packs = dict(keep_packs or {})
    packs.update(group_fingerprints(findings))
    obj = {
        "schema": BASELINE_SCHEMA,
        "packs": {pack: packs[pack] for pack in sorted(packs)},
    }
    from flake16_framework_tpu.utils.atomic import atomic_write

    with atomic_write(path, "w") as fd:
        json.dump(obj, fd, indent=1)
        fd.write("\n")
    return obj
