"""f16audit IR layer — trace the engine's REAL entry points to closed
jaxprs and walk them (ISSUE 13).

f16lint (the rest of ``analysis/``) sees source text; this module sees
the *traced program*: the planner family programs (``make_plan_fn``),
the serve AOT executables (obs/aot.py handles), and both SHAP kernels,
each traced with abstract ``ShapeDtypeStruct`` inputs — no data, no
device dispatch, seconds on the CPU backend. The walkers statically
verify the contracts PR 11–12 made load-bearing:

- callback census (I1): ``pure_callback``/``io_callback``/
  ``debug_callback`` primitives anywhere in jit-reachable code would be
  a host round-trip per dispatch — ground truth for J101's AST taint
  heuristic;
- determinism (I2): no nondeterministic primitives and no f64 avals, so
  write-ahead-journal resume stays bit-identical by construction;
- peak-memory envelope (I4): a buffer-liveness walk over the jaxpr
  (documented upper bound, see ``peak_live_bytes``) plus the lowered
  cost model, known BEFORE first silicon instead of via OOM;
- sharding audit (I5): the ``shard_map`` mesh path keeps the "config"
  axis sharded — no accidental all-gather/full replication.

IMPORT CONTRACT: this module imports jax at module level and therefore
must ONLY be imported lazily, from audit entry points (analysis/cli.py
``audit``/``--ir``, rules_ir's finding builders, sweep's budget
pre-flight). The rest of ``analysis/`` must keep working without jax
(tests/test_lint.py test_analysis_never_imports_jax).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Host-callback primitives: any of these inside a jit program is a
# device->host round trip per dispatch (I1 ground truth).
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "outside_call",
    "host_callback_call",
})
# Primitives whose results are not a pure function of their inputs —
# rng_uniform is explicitly documented as implementation-defined
# (jax.lax.rng_uniform), unlike the threefry/RBG key-based primitives.
NONDET_PRIMS = frozenset({"rng_uniform"})
# Cross-device collectives (I5): none of these may name the config axis
# inside a shard_map body — the planner's members are independent, so a
# collective over "config" is an accidental gather/replication.
COLLECTIVE_PRIMS = frozenset({
    "all_gather", "all_to_all", "psum", "pmax", "pmin", "ppermute",
    "pbroadcast", "reduce_scatter", "psum_scatter",
})
# Avals wider than f32 break the bit-identical resume contract when x64
# sneaks on (I2's promotion check).
_WIDE_DTYPES = ("float64", "complex128", "int64", "uint64")


# -- jaxpr traversal ----------------------------------------------------


def _jaxprs_in(val):
    """Sub-jaxprs inside one eqn-param value (ClosedJaxpr, Jaxpr, or
    nested lists/tuples of them — pjit/scan/while/cond/switch/shard_map
    all stash their bodies under different param shapes)."""
    if isinstance(val, jax.core.ClosedJaxpr):
        yield val.jaxpr
    elif isinstance(val, jax.core.Jaxpr):
        yield val
    elif isinstance(val, (list, tuple)):
        for v in val:
            yield from _jaxprs_in(v)


def sub_jaxprs(eqn):
    """Every sub-jaxpr one equation closes over."""
    for val in eqn.params.values():
        yield from _jaxprs_in(val)


def iter_eqns(jaxpr):
    """Depth-first walk over ALL equations, recursing through sub-jaxprs
    (the pjit wrapper, scan/while bodies, cond/switch branches,
    shard_map bodies, ...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def _open(closed_or_jaxpr):
    if isinstance(closed_or_jaxpr, jax.core.ClosedJaxpr):
        return closed_or_jaxpr.jaxpr
    return closed_or_jaxpr


def primitive_census(closed):
    """{primitive name: count} over the whole program, sub-jaxprs
    included — the raw material every I-rule filters."""
    census = {}
    for eqn in iter_eqns(_open(closed)):
        name = eqn.primitive.name
        census[name] = census.get(name, 0) + 1
    return census


# -- walkers (one per contract) -----------------------------------------


def callback_sites(closed):
    """Sorted host-callback primitive names present in the program (I1).
    Empty list == statically proven free of host round-trips."""
    census = primitive_census(closed)
    return sorted(set(census) & CALLBACK_PRIMS)


def nondet_sites(closed):
    """Sorted nondeterministic primitive names present (I2)."""
    census = primitive_census(closed)
    return sorted(set(census) & NONDET_PRIMS)


def wide_dtype_sites(closed):
    """[(primitive, dtype)] for equations producing 64-bit avals (I2's
    promotion check): under the sweep's x64-off contract these silently
    downcast; with x64 on they break bit-identical journal resume."""
    out = []
    seen = set()
    jaxpr = _open(closed)
    for v in jaxpr.invars:
        dt = str(getattr(v.aval, "dtype", ""))
        if dt in _WIDE_DTYPES and ("<input>", dt) not in seen:
            seen.add(("<input>", dt))
            out.append(("<input>", dt))
    for eqn in iter_eqns(jaxpr):
        for v in eqn.outvars:
            dt = str(getattr(getattr(v, "aval", None), "dtype", ""))
            if dt in _WIDE_DTYPES:
                key = (eqn.primitive.name, dt)
                if key not in seen:
                    seen.add(key)
                    out.append(key)
    return out


def _aval_bytes(aval):
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    size = 1
    for d in shape:
        size *= int(d)
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        # Extended dtypes (PRNG key avals like key<fry>) have no numpy
        # equivalent; their itemsize attr (2x uint32 for threefry) or a
        # conservative 8 bytes keeps the envelope an upper bound.
        itemsize = int(getattr(dtype, "itemsize", 0) or 8)
    return size * itemsize


def peak_live_bytes(closed_or_jaxpr):
    """Upper-bound peak resident bytes of one program by buffer-liveness
    walk (I4's memory envelope).

    Methodology (PROFILE.md "IR audit"): walk equations in program
    order; a var becomes live when produced (inputs/consts at entry) and
    dies after its last textual use; the peak is the max live-set byte
    total. Sub-jaxprs (scan/while/cond bodies) contribute their own
    recursive peak on top of the parent's live set minus the equation's
    own operands (they are the sub-program's inputs, not extra copies).
    This is an ENVELOPE, not a prediction: XLA fuses, rematerializes and
    double-buffers, so the true peak is usually lower — but a plan whose
    envelope exceeds the device budget is refused before dispatch
    (sweep.PlanOverBudget) rather than discovered by OOM on silicon.
    """
    jaxpr = _open(closed_or_jaxpr)
    last_use = {}
    for idx, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, jax.core.Var):
                last_use[v] = idx
    for v in jaxpr.outvars:
        if isinstance(v, jax.core.Var):
            last_use[v] = len(jaxpr.eqns)  # program outputs live to the end
    live = {}
    cur = 0
    for v in tuple(jaxpr.constvars) + tuple(jaxpr.invars):
        if v not in live:
            live[v] = _aval_bytes(v.aval)
            cur += live[v]
    peak = cur
    for idx, eqn in enumerate(jaxpr.eqns):
        for sub in sub_jaxprs(eqn):
            io = sum(_aval_bytes(v.aval) for v in eqn.invars
                     if isinstance(v, jax.core.Var))
            io += sum(_aval_bytes(v.aval) for v in eqn.outvars)
            inner = cur - io + peak_live_bytes(sub)
            peak = max(peak, inner, cur)
        for v in eqn.outvars:
            if isinstance(v, jax.core.Var) and v not in live:
                live[v] = _aval_bytes(v.aval)
                cur += live[v]
        peak = max(peak, cur)
        used = {v for v in tuple(eqn.invars) + tuple(eqn.outvars)
                if isinstance(v, jax.core.Var)}
        for v in used:
            if v in live and last_use.get(v, -1) <= idx:
                cur -= live.pop(v)
    return peak


def memory_envelope(closed):
    """The I4 pre-flight numbers for one traced program: argument bytes,
    output bytes, and the liveness-walk peak (``peak_live_bytes``)."""
    jaxpr = _open(closed)
    arg_bytes = sum(_aval_bytes(v.aval) for v in jaxpr.invars)
    out_bytes = sum(_aval_bytes(v.aval) for v in jaxpr.outvars)
    return {
        "arg_bytes": int(arg_bytes),
        "out_bytes": int(out_bytes),
        "peak_bytes": int(peak_live_bytes(jaxpr)),
    }


def lowered_cost(fn, args, kwargs=None):
    """Best-effort ``{flops, bytes_accessed}`` from the XLA cost model of
    the UNCOMPILED lowering (jax.stages.Lowered.cost_analysis — no
    device executable is built). {} when the model declines."""
    try:
        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        cost = jitted.lower(*args, **(kwargs or {})).cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if not isinstance(cost, dict):
            return {}
        return {
            "flops": float(cost.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(
                cost.get("bytes accessed", 0.0) or 0.0),
        }
    except Exception:
        return {}


def _axis_names(val):
    """Flatten a collective's axis-name param to a set of names."""
    if val is None:
        return set()
    if isinstance(val, (list, tuple, set, frozenset)):
        out = set()
        for v in val:
            out |= _axis_names(v)
        return out
    return {val}


def shard_map_audit(closed, axis="config"):
    """I5: problems with the mesh path's sharding, as strings (empty ==
    clean). Per shard_map equation:

    - at least one input must actually be sharded over ``axis`` (a mesh
      program whose every in_name drops the axis is fully replicated —
      the batch would run in full on every device);
    - every output must carry ``axis`` in its out_names (a dropped axis
      means an implicit replication/gather of per-config results);
    - no collective primitive inside the body may name ``axis``: plan
      members are independent, so a psum/all_gather over "config" is an
      accidental cross-config gather.
    Returns (n_shard_maps, problems)."""
    problems = []
    smaps = [e for e in iter_eqns(_open(closed))
             if e.primitive.name == "shard_map"]
    for i, eqn in enumerate(smaps):
        where = f"shard_map[{i}]"
        in_names = eqn.params.get("in_names", ())
        if isinstance(in_names, dict):
            in_names = (in_names,)
        sharded = any(
            axis in names
            for spec in in_names
            for names in (spec.values() if hasattr(spec, "values")
                          else ())
        )
        if in_names and not sharded:
            problems.append(
                f"{where}: no input is sharded over {axis!r} — the whole "
                "batch is replicated onto every device")
        out_names = eqn.params.get("out_names", ())
        if isinstance(out_names, dict):
            out_names = (out_names,)
        for j, spec in enumerate(out_names):
            names = set()
            for v in (spec.values() if hasattr(spec, "values") else ()):
                names |= _axis_names(v)
            if axis not in names:
                problems.append(
                    f"{where}: output {j} drops the {axis!r} axis from "
                    "out_names — per-config results would be "
                    "replicated/gathered")
        for sub in sub_jaxprs(eqn):
            for inner in iter_eqns(sub):
                if inner.primitive.name not in COLLECTIVE_PRIMS:
                    continue
                named = set()
                for key in ("axes", "axis_name", "axis_index_groups"):
                    named |= _axis_names(inner.params.get(key))
                if axis in named:
                    problems.append(
                        f"{where}: collective "
                        f"{inner.primitive.name!r} over the {axis!r} "
                        "axis — plan members are independent; this "
                        "gathers across configs")
    return len(smaps), problems


# -- entry-point tracing ------------------------------------------------


def trace_entry(fn, args, kwargs=None):
    """ClosedJaxpr of ``fn`` at abstract args. ``fn`` may be a plain
    function, a jitted callable, or an obs/aot.AotExecutableCache — the
    cache's ``traceable()`` handle is used so tracing never bumps the
    runtime dispatch census the audit reconciles against (I3)."""
    t = getattr(fn, "traceable", None)
    if callable(t):
        fn = t()[0]
    if kwargs:
        fn = functools.partial(fn, **kwargs)
    return jax.make_jaxpr(fn)(*args)


def audit_mesh(axis="config"):
    """A 1-device mesh over the local (CPU) backend — enough to trace
    the REAL shard_map program structure for the I5 audit; axis names
    and in/out_names are recorded identically at any mesh width."""
    return jax.sharding.Mesh(np.array(jax.devices()[:1]), (axis,))


def abstract_plan_args(plan, *, n_projects):
    """The ShapeDtypeStruct argument tuple of one plan's program, in
    make_plan_fn's plan_batch order: (x, y_raw, fls, preps, bals, keys,
    train_masks, test_masks, project_ids). Fold masks are float32 0/1
    (parallel/folds.fold_masks), NOT bool — the lax.switch resample
    branches require identical output dtypes."""
    n, n_feat, _n_trees, n_folds, _cap = plan.shape
    batch = plan.batch
    s = jax.ShapeDtypeStruct
    return (
        s((n, n_feat), jnp.float32),          # x (selected columns)
        s((n,), jnp.int32),                   # y_raw
        s((batch,), jnp.int32),               # flaky labels
        s((batch,), jnp.int32),               # prep codes
        s((batch,), jnp.int32),               # bal codes
        s((batch, 2), jnp.uint32),            # per-config RNG keys
        s((batch, n_folds, n), jnp.float32),  # train masks
        s((batch, n_folds, n), jnp.float32),  # test masks
        s((n,), jnp.int32),                   # project ids
    )


def trace_plan_program(plan, *, mesh=None, n_projects, max_depth=48,
                       grower=None):
    """ClosedJaxpr of one plan's whole-family program — the SAME
    ``make_plan_fn`` program SweepEngine.run_plan dispatches, traced at
    the plan's padded batch shape with abstract inputs."""
    from flake16_framework_tpu import config as cfg
    from flake16_framework_tpu.parallel import sweep

    _fs_name, model_name = plan.family
    n, n_feat, n_trees, n_folds, _cap = plan.shape
    spec = cfg.MODELS[model_name]
    if spec.n_trees != n_trees:
        spec = type(spec)(spec.name, n_trees, spec.bootstrap,
                          spec.random_splits, spec.sqrt_features)
    fn = sweep.make_plan_fn(
        spec, mesh, n=n, n_feat=n_feat, n_projects=n_projects,
        max_depth=max_depth, n_folds=n_folds, grower=grower)
    return trace_entry(fn, abstract_plan_args(plan, n_projects=n_projects))


def abstract_forest(n_trees, max_nodes, n_classes=2):
    """A ShapeDtypeStruct Forest (ops/trees.py layout) for abstract
    tracing of predict/SHAP entry points."""
    from flake16_framework_tpu.ops import trees

    s = jax.ShapeDtypeStruct
    return trees.Forest(
        feature=s((n_trees, max_nodes), jnp.int32),
        threshold=s((n_trees, max_nodes), jnp.float32),
        left=s((n_trees, max_nodes), jnp.int32),
        right=s((n_trees, max_nodes), jnp.int32),
        value=s((n_trees, max_nodes, n_classes), jnp.float32),
        n_nodes=s((n_trees,), jnp.int32),
        max_depth=s((), jnp.int32),
    )


def shap_kernel_entries(*, n_trees=100, max_nodes=64, n_samples=32,
                        n_feat=16, depth=8):
    """{name: (fn, args, kwargs)} for every SHAP engine program at one
    abstract shape: the two ladder rungs of the path-dependent work-item
    engine (xla in-graph program / pallas unit kernel on the in-graph
    layout) plus both beyond-paper modes (ISSUE 14). The pallas kernel
    is traced with interpret=True so the audit runs on hosts without a
    TPU backend — the jaxpr structure is the same; only the backend
    lowering differs."""
    from flake16_framework_tpu.ops import treeshap

    forest = abstract_forest(n_trees, max_nodes)
    x = jax.ShapeDtypeStruct((n_samples, n_feat), jnp.float32)
    bg = jax.ShapeDtypeStruct((8, n_feat), jnp.float32)
    return {
        "shap.xla": (treeshap._xla_forest_shap, (forest, x),
                     {"depth": depth}),
        "shap.pallas": (treeshap._pallas_graph_shap, (forest, x),
                        {"depth": depth, "interpret": True}),
        "shap.interventional": (treeshap._interventional_jit,
                                (forest, x, bg),
                                {"depth": depth, "row_chunk": 16}),
        "shap.interactions": (treeshap._interactions_jit, (forest, x),
                              {"depth": depth, "row_chunk": 16}),
    }


def abstract_explain_plan_args(plan):
    """The ShapeDtypeStruct argument tuple of one SHAP plan's program
    (make_shap_plan_fn's plan_batch order): (x, y_raw, fls, preps, bals,
    keys). The plan comes from planner.plan_explain_grid, whose shape
    signature appends n_explain to the fit signature."""
    n, n_feat = plan.shape[0], plan.shape[1]
    batch = plan.batch
    s = jax.ShapeDtypeStruct
    return (
        s((n, n_feat), jnp.float32),  # x (selected columns)
        s((n,), jnp.int32),           # y_raw
        s((batch,), jnp.int32),       # flaky labels
        s((batch,), jnp.int32),       # prep codes
        s((batch,), jnp.int32),       # bal codes
        s((batch, 2), jnp.uint32),    # per-config RNG keys
    )


def trace_shap_plan_program(plan, *, mesh=None, max_depth=48, mode="path",
                            n_background=8, grower=None):
    """ClosedJaxpr of one SHAP plan's whole-family EXPLAIN program — the
    SAME ``make_shap_plan_fn`` program pipeline.shap_grid dispatches,
    traced at the plan's padded batch shape with abstract inputs."""
    from flake16_framework_tpu import config as cfg
    from flake16_framework_tpu.parallel import sweep

    _fs_name, model_name = plan.family
    n, n_feat, n_trees = plan.shape[0], plan.shape[1], plan.shape[2]
    n_explain = plan.shape[-1]
    spec = cfg.MODELS[model_name]
    if spec.n_trees != n_trees:
        spec = type(spec)(spec.name, n_trees, spec.bootstrap,
                          spec.random_splits, spec.sqrt_features)
    fn = sweep.make_shap_plan_fn(
        spec, mesh, n=n, n_feat=n_feat, max_depth=max_depth,
        n_explain=n_explain, mode=mode,
        n_background=(n_background if mode == "interventional" else 0),
        grower=grower)
    return trace_entry(fn, abstract_explain_plan_args(plan))
