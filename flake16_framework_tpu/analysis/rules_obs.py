"""Rule pack 3 — telemetry schema drift (O-rules).

The drift lint that lived in tools/check_telemetry_schema.py, folded into
the f16lint engine so ``python -m flake16_framework_tpu lint`` is the one
static-analysis entry point (the tool remains as a thin shim). Two layers:

- static (check_module): emitters must only speak the declared wire
  schema — an ``obs.event("kind", ...)`` whose literal kind is missing
  from schema.EVENT_FIELDS is exactly the drift the old tool could only
  catch after a run produced a bad document (O102); span names follow
  the ``stage.detail`` lowercase convention the report renderer sorts
  and columnizes (O103).
- documents (check_docs / check_paths): validate emitted events.jsonl /
  manifest.json / ``report --json`` / ``lint --json`` captures against
  obs/schema.py (O101). Not part of the default package lint — on-disk
  runs are per-machine state, not source — but reachable via
  ``lint --telemetry PATH`` and the shim.
"""

import ast
import json
import os
import re

from flake16_framework_tpu.analysis.engine import (
    ERROR, WARNING, Finding, RuleInfo,
)
from flake16_framework_tpu.obs import schema

RULES = {r.id: r for r in (
    RuleInfo("O101", ERROR,
             "emitted telemetry document violates the wire schema"
             " (obs/schema.py)"),
    RuleInfo("O102", ERROR,
             "obs.event() with a kind not declared in schema.EVENT_FIELDS"
             " — undeclared wire-schema drift"),
    RuleInfo("O103", WARNING,
             "span name does not match the lowercase dotted convention"
             " ([a-z0-9_.]+)"),
    RuleInfo("O104", ERROR,
             "event kind emitted in code but absent from"
             " schema.EVENT_FIELDS, or declared there but never emitted"
             " — two-way wire-schema drift"),
    RuleInfo("O105", ERROR,
             "gauge/counter emitted at a call site but unregistered in"
             " the metrics census (obs/metrics.py METRIC_CENSUS) —"
             " invisible to the live exporter"),
    RuleInfo("O106", ERROR,
             "hardcoded perfdb schema-version literal outside"
             " obs/schema.py — rows must stamp schema.PERFDB_SCHEMA, or"
             " a version drift splits the database"),
    RuleInfo("O107", ERROR,
             "fleet wire-frame dict with a field outside the"
             " flake16-fleet-wire-v1 census (serve/wire.py WIRE_FIELDS),"
             " or a census field no wire-speaking module spells —"
             " two-way wire-protocol drift"),
)}

# Kinds whose emitters live OUTSIDE the package lint scope (the default
# ``lint flake16_framework_tpu/`` paths): bench.py mirrors its stage
# ledger records as ``stage`` events. Without this, the reverse O104
# direction would flag a kind that is in fact emitted.
_EXTERNAL_EMITTERS = frozenset({"stage"})

_SPAN_NAME_RE = re.compile(r"^[a-z0-9_.]+$")

# Any "flake16-perfdb-*" string constant is a perfdb schema-version
# literal; only obs/schema.py may spell one (the O106 census — the same
# single-source-of-truth discipline O104 enforces for event kinds).
_PERFDB_LITERAL_RE = re.compile(r"^flake16-perfdb-")

# Modules that SPEAK the fleet wire protocol (build or parse frames):
# O107's reverse direction scans these — and only these — for census
# field literals, so serve/wire.py's own census definition cannot
# vacuously satisfy itself.
_WIRE_SPEAKERS = ("serve/router.py", "serve/fleet.py")


def check_module(mod):
    findings = []
    in_schema = mod.path.replace(os.sep, "/").endswith("obs/schema.py")
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and _PERFDB_LITERAL_RE.match(node.value) \
                and not in_schema:
            findings.append(mod.finding(
                "O106", RULES["O106"].severity, node,
                f"perfdb schema literal {node.value!r} hardcoded here — "
                "import schema.PERFDB_SCHEMA (obs/schema.py) so one "
                "version bump cannot silently split the database"))
        if not isinstance(node, ast.Call):
            continue
        # O105 covers both call forms — obs.gauge("n", ...) and core.py's
        # own bare gauge("n", ...) — mirroring the O104 census discipline.
        fname = node.func.attr if isinstance(node.func, ast.Attribute) \
            else node.func.id if isinstance(node.func, ast.Name) else None
        if fname in ("gauge", "counter_add") and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            from flake16_framework_tpu.obs.metrics import METRIC_CENSUS

            name = node.args[0].value
            if name not in METRIC_CENSUS:
                findings.append(mod.finding(
                    "O105", RULES["O105"].severity, node,
                    f"metric {name!r} is emitted here but unregistered "
                    "in obs/metrics.py METRIC_CENSUS — the live "
                    "exporter's census cannot see it"))
        if not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr == "event" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            kind = node.args[0].value
            if kind not in schema.EVENT_FIELDS:
                findings.append(mod.finding(
                    "O102", RULES["O102"].severity, node,
                    f"event kind {kind!r} is not declared in "
                    f"schema.EVENT_FIELDS (known: "
                    f"{sorted(schema.EVENT_FIELDS)})"))
        elif node.func.attr == "span" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            name = node.args[0].value
            if not _SPAN_NAME_RE.match(name):
                findings.append(mod.finding(
                    "O103", RULES["O103"].severity, node,
                    f"span name {name!r} does not match "
                    f"{_SPAN_NAME_RE.pattern!r}"))
    return findings


def check_project(mods):
    """O104 — the two-way kind/schema consistency sweep, run once over all
    linted modules so the emit census is project-wide.

    Forward: a raw event dict literal (``{"kind": "<literal>", ...}`` —
    the low-level ``_emit``/``append_jsonl`` style that bypasses
    ``obs.event``'s O102 coverage) whose kind is not declared in
    schema.EVENT_FIELDS. ``obs.event()`` call kinds are O102's job and
    only feed the census here, so one drift never fires twice.

    Reverse: a kind declared in schema.EVENT_FIELDS that no linted module
    emits — dead schema that validators keep accepting. Anchored on the
    declaration in obs/schema.py and only checked when that module is in
    the linted set (linting a lone file must not indict the whole
    schema); kinds with known out-of-scope emitters are allowlisted
    (_EXTERNAL_EMITTERS)."""
    emitted = set()
    dict_literals = []  # (mod, kind-value node, kind)
    for mod in mods:
        for node in ast.walk(mod.tree):
            # Census counts both obs.event("k", ...) and core.py's own
            # bare event("k", ...) calls.
            if isinstance(node, ast.Call) \
                    and ((isinstance(node.func, ast.Attribute)
                          and node.func.attr == "event")
                         or (isinstance(node.func, ast.Name)
                             and node.func.id == "event")) \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                emitted.add(node.args[0].value)
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and k.value == "kind" \
                            and isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        emitted.add(v.value)
                        dict_literals.append((mod, v, v.value))

    findings = []
    for mod, node, kind in dict_literals:
        if kind not in schema.EVENT_FIELDS:
            findings.append(mod.finding(
                "O104", RULES["O104"].severity, node,
                f"event dict literal with kind {kind!r} not declared in "
                f"schema.EVENT_FIELDS (known: "
                f"{sorted(schema.EVENT_FIELDS)})"))

    schema_mod = next(
        (m for m in mods
         if m.path.replace(os.sep, "/").endswith("obs/schema.py")), None)
    if schema_mod is not None:
        for kind in sorted(set(schema.EVENT_FIELDS) - emitted
                           - _EXTERNAL_EMITTERS):
            node = _event_fields_key_node(schema_mod.tree, kind)
            if node is None:
                continue
            findings.append(schema_mod.finding(
                "O104", RULES["O104"].severity, node,
                f"event kind {kind!r} is declared in schema.EVENT_FIELDS "
                "but no linted module emits it"))
    findings += _check_wire_census(mods)
    return findings


def _load_wire_fields():
    """serve/wire.py's WIRE_FIELDS census, loaded WITHOUT executing the
    serve package __init__ (which pulls the whole serving stack — the
    lint path must stay device- and sklearn-free). Returns None when the
    module cannot load (the rule then stays silent rather than crashing
    the lint)."""
    import sys

    mod = sys.modules.get("flake16_framework_tpu.serve.wire")
    if mod is None:
        import importlib.util

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "serve", "wire.py")
        try:
            spec = importlib.util.spec_from_file_location(
                "_f16_wire_census", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception:
            return None
    return getattr(mod, "WIRE_FIELDS", None)


def _wire_frame_kind(node):
    """Which flake16-fleet-wire-v1 frame a dict literal spells, by its
    discriminating keys — request (``id`` + ``op``), response (``id`` +
    ``ok``), push (sole key ``hb``) — or None for an ordinary dict."""
    keys = {k.value for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)}
    if {"id", "op"} <= keys:
        return "request"
    if {"id", "ok"} <= keys:
        return "response"
    if keys == {"hb"}:
        return "push"
    return None


def _check_wire_census(mods):
    """O107 — the wire-field census sweep, O104's discipline applied to
    the fleet wire protocol (ISSUE 19 satellite: the trace-context
    fields ride score frames, so emitters/parsers and the census in
    serve/wire.py must not drift).

    Forward: any dict literal recognizable as a wire frame (see
    ``_wire_frame_kind``) whose string keys include a field absent from
    that frame's census entry — a frame the other end of the socket will
    silently drop fields from. Reverse: a census field that no
    wire-speaking module (_WIRE_SPEAKERS) spells as a string literal —
    dead protocol the census keeps promising; anchored on the census in
    serve/wire.py and only checked when every speaker is in the linted
    set (linting a lone file must not indict the protocol)."""
    wire_fields = _load_wire_fields()
    if not wire_fields:
        return []
    findings = []
    spoken = set()
    speakers = set()
    wire_mod = None
    for mod in mods:
        path = mod.path.replace(os.sep, "/")
        if path.endswith("serve/wire.py"):
            wire_mod = mod
        is_speaker = path.endswith(_WIRE_SPEAKERS)
        if is_speaker:
            speakers.add(os.path.basename(path))
        for node in ast.walk(mod.tree):
            if is_speaker and isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                spoken.add(node.value)
            if not isinstance(node, ast.Dict):
                continue
            frame = _wire_frame_kind(node)
            if frame is None:
                continue
            allowed = wire_fields[frame]
            for k in node.keys:
                if isinstance(k, ast.Constant) \
                        and isinstance(k.value, str) \
                        and k.value not in allowed:
                    findings.append(mod.finding(
                        "O107", RULES["O107"].severity, k,
                        f"{frame} frame field {k.value!r} is not in the "
                        "flake16-fleet-wire-v1 census (serve/wire.py "
                        f"WIRE_FIELDS[{frame!r}]: {sorted(allowed)})"))

    if wire_mod is not None and len(speakers) == len(_WIRE_SPEAKERS):
        every = set().union(*wire_fields.values())
        for field in sorted(every - spoken):
            node = _first_constant_node(wire_mod.tree, field)
            if node is None:
                continue
            findings.append(wire_mod.finding(
                "O107", RULES["O107"].severity, node,
                f"wire field {field!r} is declared in WIRE_FIELDS but "
                "no wire-speaking module "
                f"({', '.join(sorted(speakers))}) spells it"))
    return findings


def _first_constant_node(tree, value):
    """The first string-constant node equal to ``value`` (the reverse
    O107 finding's anchor inside serve/wire.py's census)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and node.value == value:
            return node
    return None


def _event_fields_key_node(tree, kind):
    """The dict-key node declaring ``kind`` inside schema.py's
    EVENT_FIELDS literal (the reverse-drift finding's anchor)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "EVENT_FIELDS"
                        for t in node.targets) \
                and isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and k.value == kind:
                    return k
    return None


# -- emitted-document validation (the old tool's body) ------------------


def check_events_file(path):
    """(n_events, problems) for one events.jsonl file."""
    problems = []
    n = 0
    with open(path) as fd:
        for lineno, line in enumerate(fd, start=1):
            if not line.strip():
                continue
            n += 1
            try:
                ev = json.loads(line)
            except ValueError as e:
                problems.append(f"{path}:{lineno}: not JSON ({e})")
                continue
            problems += [f"{path}:{lineno}: {p}"
                         for p in schema.validate_event(ev)]
    return n, problems


def check_json_file(path):
    """Problems for one JSON document — a manifest, a ``report --json``
    capture, or a ``lint --json`` capture, dispatched on its ``schema``."""
    try:
        with open(path) as fd:
            obj = json.load(fd)
    except ValueError as e:
        return [f"{path}: not JSON ({e})"]
    if isinstance(obj, dict) and obj.get("schema") == schema.REPORT_SCHEMA:
        probs = schema.validate_report(obj)
    elif isinstance(obj, dict) and obj.get("schema") == schema.LINT_SCHEMA:
        probs = schema.validate_lint_report(obj)
    else:
        probs = schema.validate_manifest(obj)
    return [f"{path}: {p}" for p in probs]


def check_run_dir(path):
    problems = []
    n_events = 0
    events = os.path.join(path, schema.EVENTS_FILE)
    manifest = os.path.join(path, schema.MANIFEST_FILE)
    if os.path.isfile(events):
        n_events, probs = check_events_file(events)
        problems += probs
    else:
        problems.append(f"{path}: no {schema.EVENTS_FILE}")
    if os.path.isfile(manifest):
        problems += check_json_file(manifest)
    else:
        problems.append(f"{path}: no {schema.MANIFEST_FILE}")
    return n_events, problems


def check_paths(paths):
    """(n_events_validated, problems) across files and run directories —
    the exact contract tools/check_telemetry_schema.py always exported
    (tests/test_obs.py pins it)."""
    n_total, problems = 0, []
    for path in paths:
        if os.path.isdir(path):
            n, probs = check_run_dir(path)
            n_total += n
            problems += probs
        elif path.endswith(".jsonl"):
            n, probs = check_events_file(path)
            n_total += n
            problems += probs
        else:
            problems += check_json_file(path)
    return n_total, problems


def check_docs(paths):
    """Document problems as O101 findings (the ``lint --telemetry PATH``
    path). Each problem string already carries its own path context."""
    _, problems = check_paths(paths)
    return [Finding("O101", RULES["O101"].severity, str(p).split(":")[0],
                    0, 0, p, snippet=p)
            for p in problems]
