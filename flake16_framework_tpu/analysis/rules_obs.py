"""Rule pack 3 — telemetry schema drift (O-rules).

The drift lint that lived in tools/check_telemetry_schema.py, folded into
the f16lint engine so ``python -m flake16_framework_tpu lint`` is the one
static-analysis entry point (the tool remains as a thin shim). Two layers:

- static (check_module): emitters must only speak the declared wire
  schema — an ``obs.event("kind", ...)`` whose literal kind is missing
  from schema.EVENT_FIELDS is exactly the drift the old tool could only
  catch after a run produced a bad document (O102); span names follow
  the ``stage.detail`` lowercase convention the report renderer sorts
  and columnizes (O103).
- documents (check_docs / check_paths): validate emitted events.jsonl /
  manifest.json / ``report --json`` / ``lint --json`` captures against
  obs/schema.py (O101). Not part of the default package lint — on-disk
  runs are per-machine state, not source — but reachable via
  ``lint --telemetry PATH`` and the shim.
"""

import ast
import json
import os
import re

from flake16_framework_tpu.analysis.engine import (
    ERROR, WARNING, Finding, RuleInfo,
)
from flake16_framework_tpu.obs import schema

RULES = {r.id: r for r in (
    RuleInfo("O101", ERROR,
             "emitted telemetry document violates the wire schema"
             " (obs/schema.py)"),
    RuleInfo("O102", ERROR,
             "obs.event() with a kind not declared in schema.EVENT_FIELDS"
             " — undeclared wire-schema drift"),
    RuleInfo("O103", WARNING,
             "span name does not match the lowercase dotted convention"
             " ([a-z0-9_.]+)"),
)}

_SPAN_NAME_RE = re.compile(r"^[a-z0-9_.]+$")


def check_module(mod):
    findings = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr == "event" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            kind = node.args[0].value
            if kind not in schema.EVENT_FIELDS:
                findings.append(mod.finding(
                    "O102", RULES["O102"].severity, node,
                    f"event kind {kind!r} is not declared in "
                    f"schema.EVENT_FIELDS (known: "
                    f"{sorted(schema.EVENT_FIELDS)})"))
        elif node.func.attr == "span" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            name = node.args[0].value
            if not _SPAN_NAME_RE.match(name):
                findings.append(mod.finding(
                    "O103", RULES["O103"].severity, node,
                    f"span name {name!r} does not match "
                    f"{_SPAN_NAME_RE.pattern!r}"))
    return findings


# -- emitted-document validation (the old tool's body) ------------------


def check_events_file(path):
    """(n_events, problems) for one events.jsonl file."""
    problems = []
    n = 0
    with open(path) as fd:
        for lineno, line in enumerate(fd, start=1):
            if not line.strip():
                continue
            n += 1
            try:
                ev = json.loads(line)
            except ValueError as e:
                problems.append(f"{path}:{lineno}: not JSON ({e})")
                continue
            problems += [f"{path}:{lineno}: {p}"
                         for p in schema.validate_event(ev)]
    return n, problems


def check_json_file(path):
    """Problems for one JSON document — a manifest, a ``report --json``
    capture, or a ``lint --json`` capture, dispatched on its ``schema``."""
    try:
        with open(path) as fd:
            obj = json.load(fd)
    except ValueError as e:
        return [f"{path}: not JSON ({e})"]
    if isinstance(obj, dict) and obj.get("schema") == schema.REPORT_SCHEMA:
        probs = schema.validate_report(obj)
    elif isinstance(obj, dict) and obj.get("schema") == schema.LINT_SCHEMA:
        probs = schema.validate_lint_report(obj)
    else:
        probs = schema.validate_manifest(obj)
    return [f"{path}: {p}" for p in probs]


def check_run_dir(path):
    problems = []
    n_events = 0
    events = os.path.join(path, schema.EVENTS_FILE)
    manifest = os.path.join(path, schema.MANIFEST_FILE)
    if os.path.isfile(events):
        n_events, probs = check_events_file(events)
        problems += probs
    else:
        problems.append(f"{path}: no {schema.EVENTS_FILE}")
    if os.path.isfile(manifest):
        problems += check_json_file(manifest)
    else:
        problems.append(f"{path}: no {schema.MANIFEST_FILE}")
    return n_events, problems


def check_paths(paths):
    """(n_events_validated, problems) across files and run directories —
    the exact contract tools/check_telemetry_schema.py always exported
    (tests/test_obs.py pins it)."""
    n_total, problems = 0, []
    for path in paths:
        if os.path.isdir(path):
            n, probs = check_run_dir(path)
            n_total += n
            problems += probs
        elif path.endswith(".jsonl"):
            n, probs = check_events_file(path)
            n_total += n
            problems += probs
        else:
            problems += check_json_file(path)
    return n_total, problems


def check_docs(paths):
    """Document problems as O101 findings (the ``lint --telemetry PATH``
    path). Each problem string already carries its own path context."""
    _, problems = check_paths(paths)
    return [Finding("O101", RULES["O101"].severity, str(p).split(":")[0],
                    0, 0, p, snippet=p)
            for p in problems]
