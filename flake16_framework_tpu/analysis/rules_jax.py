"""Rule pack 1 — JAX/TPU hygiene (J-rules).

The defect classes that turn a multi-hour 216-config sweep into a wasted
allocation (ISSUE 2; PROFILE.md round-3: per-dispatch tunnel round-trips,
not compute, dominate per-config cost):

- implicit host syncs inside jit-reachable code (J101-J104): a
  ``float()`` or ``if`` on a traced array forces a device readback per
  call — invisible in code review, obvious in a profiler after the run;
- retrace hazards (J201-J203): unhashable statics, order-unstable set
  iteration feeding closures, and jit-in-a-loop all recompile per config
  instead of once per model family (the sweep's whole compile economy,
  parallel/sweep.py);
- dtype drift (J301): an explicit float64 under disabled x64 silently
  downcasts — parity bugs that surface as F1 noise, not errors;
- leftover instrumentation (J401, J402): ``jax.debug.print`` and
  per-iteration ``block_until_ready`` serialize the dispatch pipeline;
- serving hot-path hygiene (J601, ISSUE 6): blocking device->host
  transfers in the scoring service's request path (serve/batcher.py and
  serve/queue.py by location, plus any function decorated with
  ``serve.hot_path``) stall the microbatch pipeline — the one sanctioned
  crossing per microbatch carries an inline ``f16lint: disable=J601``;
- durable-artifact write hygiene (J701, ISSUE 11): a bare write-mode
  ``open(..., "w"/"wb")`` tears the artifact when a preemption SIGKILL
  lands mid-write — durable writes go through ``utils.atomic_write``
  (tmp + fsync + rename). Append mode is exempt (the O_APPEND JSONL
  sink's whole-line semantics are the sanctioned crash contract), as
  are the two modules that ARE the durability layer
  (utils/atomic.py, resilience/journal.py); standalone plugins that
  cannot import the package carry inline disables.

Reachability is a module-local static approximation: a function is
*jit-reachable* when it is decorated with ``jax.jit`` (bare or via
``functools.partial``), passed by name to a jit/vmap/shard_map/lax
combinator, or (transitively) defined in or called from such a function.
*Traced* names originate from jnp/jax.random/jax.lax/jax.nn calls within
the same function (parameters are deliberately NOT assumed traced —
static_argnames and host drivers would drown the signal in false
positives); ``.shape``/``.dtype``/``.ndim``/``len()`` derivations are
host values and break the taint.
"""

import ast

from flake16_framework_tpu.analysis.engine import (
    ERROR, WARNING, RuleInfo,
)

RULES = {r.id: r for r in (
    RuleInfo("J101", ERROR,
             "float()/int()/bool() on a traced value in jit-reachable code"
             " — implicit host sync per call"),
    RuleInfo("J102", ERROR,
             ".item() in jit-reachable code — device->host readback"),
    RuleInfo("J103", ERROR,
             "np.asarray/np.array on a traced value in jit-reachable code"
             " — silent device->host transfer"),
    RuleInfo("J104", ERROR,
             "Python if/while on a traced value — ConcretizationTypeError"
             " under jit, or a silent host sync outside it"),
    RuleInfo("J201", WARNING,
             "static_argnums/static_argnames given a mutable literal —"
             " unhashable statics retrace (or TypeError) per call"),
    RuleInfo("J202", WARNING,
             "iteration over a set — nondeterministic order; feeding jit"
             " closures or sweep schedules makes retraces run-dependent"),
    RuleInfo("J203", WARNING,
             "jax.jit called inside a loop body — a fresh wrapper per"
             " iteration defeats the trace cache (retrace per config)"),
    RuleInfo("J301", ERROR,
             "explicit float64 dtype in a jnp call — silently downcast"
             " to float32 when jax_enable_x64 is off"),
    RuleInfo("J401", ERROR,
             "leftover jax.debug.print/jax.debug.breakpoint"),
    RuleInfo("J402", WARNING,
             "block_until_ready inside a loop body — serializes the"
             " dispatch pipeline (one tunnel round-trip per iteration)"),
    RuleInfo("J501", WARNING,
             "broad except around a device dispatch without routing the"
             " failure through the resilience layer — faults vanish"
             " unclassified instead of retrying/degrading/quarantining"),
    RuleInfo("J601", WARNING,
             "blocking device->host transfer in serve hot-path scope —"
             " stalls the microbatch pipeline; transfers belong at the"
             " batch boundary (one amortized crossing per microbatch)"),
    RuleInfo("J701", WARNING,
             "write-mode open() outside utils.atomic_write — a crash or"
             " preemption mid-write tears the durable artifact; use"
             " atomic_write (tmp + fsync + rename)"),
)}

# Call roots whose results are traced arrays (after alias resolution).
_TRACED_ROOTS = (
    "jax.numpy.", "jax.random.", "jax.lax.", "jax.nn.", "jax.scipy.",
)
# Combinators whose function arguments become jit-reachable.
_JIT_WRAPPERS = {
    "jax.jit", "jax.pmap", "jax.vmap", "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.lax.scan", "jax.lax.cond", "jax.lax.switch",
    "jax.lax.while_loop", "jax.lax.fori_loop", "jax.lax.map",
    "jax.checkpoint", "jax.remat", "jax.grad", "jax.value_and_grad",
}
# Attribute access that turns a traced value back into a host value.
_HOST_ATTRS = {"shape", "dtype", "ndim", "size"}

# J501: calls where device faults of a dispatch actually surface — a
# broad except around one of these is handling DEVICE failures, and must
# hand them to the resilience layer (classify / guard / ladder) rather
# than swallow them unclassified. ``.block_until_ready()`` attribute
# calls count too (matched structurally below).
_DISPATCH_MARKERS = {"jax.block_until_ready", "jax.device_get"}
# Any call resolving under this package counts as "routed": the
# classifier (faults.classify*), the guard, a ladder step, ...
_RESILIENCE_ROOT = "flake16_framework_tpu.resilience"
_BROAD_EXCEPTS = {"Exception", "BaseException", "builtins.Exception",
                  "builtins.BaseException"}

# J601: calls that force a device->host transfer (or a full pipeline
# drain) when they land in serve hot-path scope. Bare
# ``.block_until_ready()`` attribute calls count too.
_HOT_BLOCKING = {"jax.block_until_ready", "jax.device_get",
                 "numpy.asarray", "numpy.array"}
# Modules that are hot-path scope by location (repo-relative posix).
_HOT_MODULES = ("batcher.py", "queue.py")

# J701: the durability layer itself — raw fd control (fsync'd appends,
# tmp-file plumbing) is its job, so write-mode open() is sanctioned here
# and nowhere else.
_ATOMIC_EXEMPT = ("utils/atomic.py", "resilience/journal.py")


def _import_aliases(tree):
    """local name -> dotted module path, from import statements."""
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _dotted(node, aliases):
    """Resolve Name/Attribute chains to a dotted path with import aliases
    applied (``jnp.zeros`` -> ``jax.numpy.zeros``); None for non-chains."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    parts.reverse()
    root = aliases.get(parts[0], parts[0])
    return ".".join([root] + parts[1:])


def _is_set_expr(node, aliases):
    if isinstance(node, ast.Set):
        return True
    if isinstance(node, ast.Call):
        return _dotted(node.func, aliases) in ("set", "frozenset")
    return False


class _Reach:
    """The module's jit-reachable function set (see module docstring)."""

    def __init__(self, tree, aliases):
        self.aliases = aliases
        # name -> [FunctionDef] (any nesting level; approximation)
        self.defs_by_name = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)
        seeds = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(self._jit_decorator(d) for d in node.decorator_list):
                    seeds.add(node)
            elif isinstance(node, ast.Call):
                if _dotted(node.func, aliases) in _JIT_WRAPPERS:
                    for ref in ast.walk(node):
                        if isinstance(ref, ast.Name):
                            seeds.update(self.defs_by_name.get(ref.id, ()))
        # Transitive closure: nested defs of a reachable function, and
        # module-local functions it calls by name.
        reachable = set()
        frontier = list(seeds)
        while frontier:
            fn = frontier.pop()
            if fn in reachable:
                continue
            reachable.add(fn)
            for node in ast.walk(fn):
                if node is not fn and isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    frontier.append(node)
                elif isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Name):
                    frontier.extend(
                        self.defs_by_name.get(node.func.id, ()))
        self.reachable = reachable

    def _jit_decorator(self, dec):
        d = _dotted(dec, self.aliases)
        if d in ("jax.jit", "jit", "jax.pmap"):
            return True
        if isinstance(dec, ast.Call):
            f = _dotted(dec.func, self.aliases)
            if f in ("jax.jit", "jit", "jax.pmap"):
                return True
            if f in ("functools.partial", "partial") and dec.args:
                return _dotted(dec.args[0], self.aliases) in (
                    "jax.jit", "jit", "jax.pmap")
        return False


def _traced_names(fn, aliases):
    """Names in ``fn`` (own body only, nested defs excluded) assigned from
    jnp/jax.random/jax.lax/... calls, with taint propagation through
    expressions; ``.shape``-like access and len() break the taint."""
    traced = set()

    def own_nodes(root):
        stack = [root]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if node is not root and isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                    continue
                stack.append(child)

    def expr_traced(node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr in _HOST_ATTRS:
                return False  # .shape chains are host-side
            if isinstance(sub, ast.Call):
                d = _dotted(sub.func, aliases)
                if d is not None:
                    if d.startswith(_TRACED_ROOTS):
                        return True
                    if d in ("len", "int", "float", "bool"):
                        return False
            if isinstance(sub, ast.Name) and sub.id in traced:
                return True
        return False

    # Two passes so a use-before-def ordering in the source (rare) still
    # converges for the common single-assignment case.
    for _ in range(2):
        for node in own_nodes(fn):
            targets = ()
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = (node.target,)
                value = node.value
            if value is None or not expr_traced(value):
                continue
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        traced.add(sub.id)
    return traced


def check_module(mod):
    aliases = _import_aliases(mod.tree)
    reach = _Reach(mod.tree, aliases)
    findings = []

    def emit(rule_id, node, message):
        findings.append(
            mod.finding(rule_id, RULES[rule_id].severity, node, message))

    # -- whole-module rules (host code included) ------------------------
    loop_depth = 0

    def walk(node):
        nonlocal loop_depth
        is_loop = isinstance(node, (ast.For, ast.While, ast.AsyncFor))
        if is_loop:
            loop_depth += 1
        if isinstance(node, ast.Call):
            d = _dotted(node.func, aliases)
            if d in ("jax.debug.print", "jax.debug.breakpoint"):
                emit("J401", node, f"{d} left in code")
            if d == "jax.block_until_ready" and loop_depth:
                emit("J402", node,
                     "jax.block_until_ready inside a loop body")
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "block_until_ready"
                    and d != "jax.block_until_ready" and loop_depth):
                emit("J402", node, ".block_until_ready() inside a loop body")
            if d in _JIT_WRAPPERS and d.endswith(".jit") and loop_depth:
                emit("J203", node, "jax.jit inside a loop body")
            is_jit_call = d in _JIT_WRAPPERS or (
                d in ("functools.partial", "partial") and node.args
                and _dotted(node.args[0], aliases) in _JIT_WRAPPERS)
            if is_jit_call:
                for kw in node.keywords:
                    if kw.arg in ("static_argnums", "static_argnames") \
                            and isinstance(kw.value,
                                           (ast.List, ast.Set, ast.Dict)):
                        emit("J201", kw.value,
                             f"{kw.arg} should be a tuple, not a "
                             f"{type(kw.value).__name__.lower()} literal")
            if d == "jax.numpy.array" or (d or "").startswith("jax.numpy."):
                for kw in node.keywords:
                    if kw.arg == "dtype" and _is_f64(kw.value, aliases):
                        emit("J301", kw.value,
                             "explicit float64 dtype in a jnp call")
        if isinstance(node, (ast.For, ast.AsyncFor)) \
                and _is_set_expr(node.iter, aliases):
            emit("J202", node.iter, "iterating a set (unordered); use "
                 "sorted(...) for a deterministic schedule")
        if isinstance(node, ast.comprehension) \
                and _is_set_expr(node.iter, aliases):
            emit("J202", node.iter, "comprehension over a set (unordered);"
                 " use sorted(...)")
        if isinstance(node, ast.Attribute) \
                and _dotted(node, aliases) == "jax.numpy.float64":
            emit("J301", node, "jnp.float64 is float32 when x64 is off")
        for child in ast.iter_child_nodes(node):
            walk(child)
        if is_loop:
            loop_depth -= 1

    walk(mod.tree)

    # -- J501: unguarded broad excepts around device dispatches ---------

    def has_dispatch(stmts):
        for stmt in stmts:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                if _dotted(sub.func, aliases) in _DISPATCH_MARKERS:
                    return True
                if isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "block_until_ready":
                    return True
        return False

    def routes_resilience(handler):
        for sub in ast.walk(handler):
            if isinstance(sub, ast.Call):
                d = _dotted(sub.func, aliases)
                if d and (d == _RESILIENCE_ROOT
                          or d.startswith(_RESILIENCE_ROOT + ".")):
                    return True
        return False

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Try) or not has_dispatch(node.body):
            continue
        for h in node.handlers:
            broad = h.type is None \
                or _dotted(h.type, aliases) in _BROAD_EXCEPTS
            if broad and not routes_resilience(h):
                emit("J501", h,
                     "except Exception around a device dispatch must route"
                     " the failure through flake16_framework_tpu.resilience"
                     " (classify / guard / ladder), not swallow it")

    # -- J601: blocking transfers in serve hot-path scope ---------------
    hot_module = ("serve/" in mod.path
                  and mod.path.rsplit("/", 1)[-1] in _HOT_MODULES)

    def hot_decorated(fn):
        for dec in fn.decorator_list:
            d = _dotted(dec, aliases)
            if d == "hot_path" or (d or "").endswith(".hot_path"):
                return True
        return False

    def scan_hot(root, where):
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func, aliases)
            if d in _HOT_BLOCKING:
                emit("J601", node,
                     f"{d} in serve hot path ({where}) — blocking "
                     "device->host transfer; move it to the batch "
                     "boundary")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready":
                emit("J601", node,
                     f".block_until_ready() in serve hot path ({where})")

    if hot_module:
        scan_hot(mod.tree, f"hot module {mod.path.rsplit('/', 1)[-1]}")
    else:
        for fnode in ast.walk(mod.tree):
            if isinstance(fnode, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and hot_decorated(fnode):
                scan_hot(fnode, f"@hot_path function {fnode.name!r}")

    # -- J701: durable writes bypassing utils.atomic_write --------------
    if not mod.path.endswith(_ATOMIC_EXEMPT):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _dotted(node.func, aliases) not in ("open", "io.open"):
                continue
            mode = node.args[1] if len(node.args) >= 2 else None
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if isinstance(mode, ast.Constant) \
                    and isinstance(mode.value, str) \
                    and ("w" in mode.value or "x" in mode.value):
                emit("J701", node,
                     f"open(..., {mode.value!r}) writes a durable "
                     "artifact without tmp+fsync+rename; wrap it in "
                     "utils.atomic_write")

    # -- jit-reachable-only rules --------------------------------------
    for fn in reach.reachable:
        traced = _traced_names(fn, aliases)

        def own_walk(root):
            stack = list(ast.iter_child_nodes(root))
            while stack:
                node = stack.pop()
                yield node
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # nested defs are visited as their own fn
                stack.extend(ast.iter_child_nodes(node))

        def uses_traced(node):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Attribute) \
                        and sub.attr in _HOST_ATTRS:
                    return False
                if isinstance(sub, ast.Name) and sub.id in traced:
                    return True
            return False

        for node in own_walk(fn):
            if isinstance(node, ast.Call):
                d = _dotted(node.func, aliases)
                if d in ("float", "int", "bool") and node.args \
                        and uses_traced(node.args[0]):
                    emit("J101", node,
                         f"{d}() on a traced value in jit-reachable "
                         f"function {fn.name!r}")
                elif d in ("numpy.asarray", "numpy.array") and node.args \
                        and uses_traced(node.args[0]):
                    emit("J103", node,
                         f"{d.replace('numpy', 'np')} on a traced value "
                         f"in jit-reachable function {fn.name!r}")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" and not node.args:
                    emit("J102", node,
                         f".item() in jit-reachable function {fn.name!r}")
            elif isinstance(node, (ast.If, ast.While)) \
                    and uses_traced(node.test):
                kw = "while" if isinstance(node, ast.While) else "if"
                emit("J104", node,
                     f"Python `{kw}` on a traced value in jit-reachable "
                     f"function {fn.name!r} (use jnp.where/lax.cond)")
    return findings


def _is_f64(node, aliases):
    if isinstance(node, ast.Constant) and node.value in (
            "float64", "f8", "double"):
        return True
    d = _dotted(node, aliases)
    return d in ("numpy.float64", "jax.numpy.float64", "float64")
