"""f16race concurrency model — thread topology + lock-set machinery.

The shared substrate under analysis/rules_conc.py (the C101–C503 pack)
and obs/lockwatch.py's runtime reconciliation (PROFILE.md "Concurrency
audit"). Pure AST + stdlib: nothing here imports jax (the analysis
package contract) or even the rest of the package.

The model follows the RacerD lineage (PAPERS.md): a *compositional*
lock-set analysis with no whole-program may-alias reasoning. Three
artifacts come out of a project build:

- **Thread topology** — which functions can run on which thread roots.
  Roots are discovered, not declared: ``threading.Thread(target=…)`` /
  ``threading.Timer``, ``ThreadingHTTPServer`` handler classes, and
  ``signal.signal`` handlers. The implicit ``main`` root reaches public
  functions (and dunders), anything called at module top level, and
  ``atexit`` hooks; underscore-private functions are reachable only
  where a resolvable call reaches them. A root is *multi-instance*
  when its ``Thread(...)`` call sits inside a loop or comprehension
  (a dispatcher pool counts as ≥2 writers by itself).
- **Lock census + lock-order graph** — every ``threading.Lock/RLock/
  Condition/Semaphore`` creation gets a stable id
  (``path:Class.attr`` / ``path:global`` / ``path:fn.local``) and a
  creation *site* (``path:lineno``) — the join key lockwatch uses to
  map dynamically observed locks back onto this model. Order edges
  come from lexically nested ``with``/``acquire()`` pairs plus one
  interprocedural hop: per-function *may-acquire* summaries propagated
  to fixpoint over resolvable calls (bare names, ``self.method``,
  ``alias.func`` through imports with one ``__init__`` re-export hop).
- **Shared-state census** — writes to ``self.`` attributes, module
  globals (including ``G.attr = …`` / ``G[k] = …`` mutation through a
  global name), and closure cells, each annotated with the lock set
  held at the write and the thread roots that reach the writer.

Known approximations (deliberate; documented in PROFILE.md): calls
through arbitrary attributes (``self.guard.call``) do not propagate
reachability or summaries; container mutation via method call
(``xs.append``) is not a tracked write; ``release()`` is assumed to
unwind in the block it was acquired in.
"""

import ast
import os

LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}
THREAD_FACTORIES = {"threading.Thread", "threading.Timer"}

MAIN_ROOT = "main"

_LOOPS = (ast.For, ast.While, ast.ListComp, ast.SetComp, ast.DictComp,
          ast.GeneratorExp, ast.AsyncFor)


def import_aliases(tree):
    """name -> dotted module/object path, from import statements."""
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted(node, aliases):
    """Attribute/Name chain -> dotted path with aliases resolved."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    head = aliases.get(node.id, node.id)
    parts.append(head)
    return ".".join(reversed(parts))


def module_dotted(path):
    """Repo-relative path -> importable dotted name (best effort)."""
    p = path[:-3] if path.endswith(".py") else path
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class LockDef:
    __slots__ = ("id", "site", "path", "kind")

    def __init__(self, lock_id, site, path, kind):
        self.id, self.site, self.path, self.kind = lock_id, site, path, kind


class ThreadRoot:
    """One discovered thread entry point."""

    __slots__ = ("key", "kind", "path", "target", "multi", "name", "node")

    def __init__(self, key, kind, path, target, multi, name, node):
        self.key, self.kind, self.path = key, kind, path
        self.target, self.multi = target, multi
        self.name, self.node = name, node


class CallRec:
    __slots__ = ("spec", "node", "held", "dotted", "attr", "recv_lock")

    def __init__(self, spec, node, held, dotted=None, attr=None,
                 recv_lock=None):
        self.spec, self.node, self.held = spec, node, held
        self.dotted, self.attr, self.recv_lock = dotted, attr, recv_lock


class WriteRec:
    __slots__ = ("obj", "node", "held")

    def __init__(self, obj, node, held):
        self.obj, self.node, self.held = obj, node, held


class FuncModel:
    __slots__ = ("qualname", "node", "class_name", "path", "decorators",
                 "direct_locks", "edges", "calls", "writes", "local_locks",
                 "local_names", "global_decls", "is_method")

    def __init__(self, qualname, node, class_name, path):
        self.qualname, self.node = qualname, node
        self.class_name, self.path = class_name, path
        self.decorators = []
        self.global_decls = set()
        self.is_method = False
        self.direct_locks = set()
        self.edges = []          # (held_id, acquired_id, node)
        self.calls = []          # [CallRec]
        self.writes = []         # [WriteRec]
        self.local_locks = {}    # name -> lock id
        self.local_names = set()

    @property
    def public(self):
        last = self.qualname.rsplit(".", 1)[-1]
        if last.startswith("__") and last.endswith("__"):
            return True      # dunders run implicitly from user code
        return not last.startswith("_")


class ModuleModel:
    def __init__(self, path, tree):
        self.path = path
        self.tree = tree
        self.dotted = module_dotted(path)
        self.aliases = import_aliases(tree)
        self.funcs = {}            # qualname -> FuncModel
        self.classes = {}          # name -> ClassDef (incl. nested)
        self.locks = {}            # lock id -> LockDef
        self.global_locks = {}     # global name -> lock id
        self.attr_locks = {}       # (class, attr) -> lock id
        self.global_names = set()  # module-level assigned names
        self.roots = []            # [ThreadRoot]
        self.signal_handlers = []  # (handler FuncModel|None, node)
        self.toplevel_called = set()
        self.reexports = {}        # name -> dotted source (ImportFrom)
        _scan_module(self)

    @property
    def has_threads(self):
        return any(r.kind in ("thread", "httpserver") for r in self.roots)


# -- per-module scan ------------------------------------------------------


def _lock_factory(call, aliases):
    if not isinstance(call, ast.Call):
        return None
    d = dotted(call.func, aliases)
    return d if d in LOCK_FACTORIES else None


def _scan_module(mm):
    tree = mm.tree
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            mm.classes[node.name] = node
    for node in tree.body:
        if isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                mm.reexports[a.asname or a.name] = \
                    f"{node.module}.{a.name}"
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Name):
                    mm.global_names.add(t.id)
            fac = _lock_factory(getattr(node, "value", None), mm.aliases)
            if fac:
                for t in targets:
                    if isinstance(t, ast.Name):
                        lid = f"{mm.path}:{t.id}"
                        mm.locks[lid] = LockDef(
                            lid, f"{mm.path}:{node.value.lineno}",
                            mm.path, fac)
                        mm.global_locks[t.id] = lid
        elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            base = node.value.func
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                mm.toplevel_called.add(base.id)
            d = dotted(node.value.func, mm.aliases)
            if d == "atexit.register" and node.value.args:
                a0 = node.value.args[0]
                if isinstance(a0, ast.Name):
                    mm.toplevel_called.add(a0.id)

    # Class-attribute locks: ``self.X = threading.Lock()`` in any method.
    for cname, cnode in mm.classes.items():
        for meth in cnode.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for st in ast.walk(meth):
                if not isinstance(st, ast.Assign):
                    continue
                fac = _lock_factory(st.value, mm.aliases)
                if not fac:
                    continue
                for t in st.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        lid = f"{mm.path}:{cname}.{t.attr}"
                        mm.locks[lid] = LockDef(
                            lid, f"{mm.path}:{st.value.lineno}",
                            mm.path, fac)
                        mm.attr_locks[(cname, t.attr)] = lid

    # Function models (module functions, methods, nested defs).
    def visit_scope(body, prefix, class_name, in_class):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{node.name}" if prefix else node.name
                fm = FuncModel(q, node, class_name, mm.path)
                fm.is_method = in_class
                fm.decorators = [dotted(d, mm.aliases) or
                                 getattr(d, "attr", None) or
                                 (d.id if isinstance(d, ast.Name) else None)
                                 for d in node.decorator_list]
                mm.funcs[q] = fm
                visit_scope(node.body, q + ".", class_name, False)
            elif isinstance(node, ast.ClassDef):
                visit_scope(node.body, f"{node.name}.", node.name, True)
    visit_scope(tree.body, "", None, False)

    for fm in list(mm.funcs.values()):
        _walk_function(mm, fm)

    # Thread / signal / http-server roots anywhere in the module.
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func, mm.aliases)
        if d in THREAD_FACTORIES:
            target = None
            for kw in node.keywords:
                if kw.arg in ("target", "function"):
                    target = kw.value
            if target is None and d.endswith("Timer") and len(node.args) > 1:
                target = node.args[1]
            name = None
            for kw in node.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    name = kw.value.value
            spec = _target_spec(target, mm)
            key = f"thread:{mm.path}:{node.lineno}"
            mm.roots.append(ThreadRoot(
                key, "thread", mm.path, spec,
                _in_loop(tree, node), name, node))
        elif d and d.endswith("ThreadingHTTPServer") and len(node.args) >= 2:
            h = node.args[1]
            if isinstance(h, ast.Name) and h.id in mm.classes:
                for meth in mm.classes[h.id].body:
                    if isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        key = f"httpserver:{mm.path}:{node.lineno}"
                        mm.roots.append(ThreadRoot(
                            key, "httpserver", mm.path,
                            ("qual", f"{h.id}.{meth.name}"), True,
                            h.id, node))
        elif d == "signal.signal" and len(node.args) >= 2:
            handler = node.args[1]
            spec = _target_spec(handler, mm)
            key = f"signal:{mm.path}:{node.lineno}"
            mm.roots.append(ThreadRoot(
                key, "signal", mm.path, spec, False, None, node))
            mm.signal_handlers.append((spec, handler, node))


def _target_spec(target, mm):
    """A thread-target / handler expression -> resolution spec."""
    if target is None:
        return None
    if isinstance(target, ast.Name):
        return ("name", target.id)
    if isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name) and target.value.id == "self":
        # The enclosing class is unknown at the module-wide walk; the
        # project phase matches the method name against every class.
        return ("selfattr", target.attr)
    if isinstance(target, ast.Lambda):
        q = f"<lambda>:{target.lineno}"
        fm = FuncModel(q, target, None, mm.path)
        mm.funcs[q] = fm
        _walk_function(mm, fm)
        return ("qual", q)
    d = dotted(target, mm.aliases)
    return ("dotted", d) if d else None


def _in_loop(tree, node):
    """Whether ``node`` sits inside a loop or comprehension."""
    found = [False]

    def rec(n, depth):
        if n is node:
            found[0] = depth > 0
            return True
        bump = 1 if isinstance(n, _LOOPS) else 0
        for c in ast.iter_child_nodes(n):
            if rec(c, depth + bump):
                return True
        return False
    rec(tree, 0)
    return found[0]


# -- per-function lock-set walk -------------------------------------------


def _walk_function(mm, fm):
    node = fm.node
    body = node.body if not isinstance(node, ast.Lambda) else [
        ast.Expr(value=node.body)]
    # Local name census (params + any Name store) — shadow detection.
    args = getattr(node, "args", None)
    if args is not None:
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            fm.local_names.add(a.arg)
    own_stmts = _own_statements(body)
    for st in own_stmts:
        for n in ast.walk(st):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                fm.local_names.add(n.id)
    fm.global_decls = set()
    for st in own_stmts:
        for n in ast.walk(st):
            if isinstance(n, ast.Global):
                fm.global_decls.update(n.names)
    # Function-local lock creations.
    for st in own_stmts:
        for n in ast.walk(st):
            if isinstance(n, ast.Assign):
                fac = _lock_factory(n.value, mm.aliases)
                if fac:
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            lid = f"{mm.path}:{fm.qualname}.{t.id}"
                            mm.locks[lid] = LockDef(
                                lid, f"{mm.path}:{n.value.lineno}",
                                mm.path, fac)
                            fm.local_locks[t.id] = lid
    _walk_body(mm, fm, body, ())


def _own_statements(body):
    """Statements of a function excluding nested function bodies."""
    out = []

    def rec(stmts):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            out.append(st)
            for field in ("body", "orelse", "finalbody"):
                rec(getattr(st, field, []) or [])
            for h in getattr(st, "handlers", []) or []:
                rec(h.body)
    rec(body)
    return out


def resolve_lock(mm, fm, expr):
    """Lock id for a Name/Attribute expression, else None.

    Lookup order: function locals (chained through enclosing functions
    by qualname prefix), ``self.attr`` against the enclosing class,
    module globals, then ``alias.attr`` as an extern placeholder the
    project phase resolves against other modules' global locks.
    """
    if isinstance(expr, ast.Name):
        f = fm
        while f is not None:
            if expr.id in f.local_locks:
                return f.local_locks[expr.id]
            outer = f.qualname.rsplit(".", 1)[0] \
                if "." in f.qualname else None
            f = mm.funcs.get(outer) if outer else None
        return mm.global_locks.get(expr.id)
    if isinstance(expr, ast.Attribute):
        if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and fm.class_name:
            return mm.attr_locks.get((fm.class_name, expr.attr))
        d = dotted(expr, mm.aliases)
        if d:
            return "extern::" + d
    return None


def _walk_body(mm, fm, stmts, held):
    open_locks = []
    for st in stmts:
        h = held + tuple(open_locks)
        acq = _acquire_target(mm, fm, st, "acquire")
        rel = _acquire_target(mm, fm, st, "release")
        if acq is not None:
            _note_acquire(fm, h, acq, st)
            open_locks.append(acq)
            continue
        if rel is not None:
            if rel in open_locks:
                open_locks.remove(rel)
            continue
        _walk_stmt(mm, fm, st, h)


def _acquire_target(mm, fm, st, method):
    if not (isinstance(st, ast.Expr) and isinstance(st.value, ast.Call)):
        return None
    call = st.value
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == method):
        return None
    return resolve_lock(mm, fm, call.func.value)


def _note_acquire(fm, held, lock_id, node):
    fm.direct_locks.add(lock_id)
    for h in held:
        if h != lock_id:
            fm.edges.append((h, lock_id, node))


def _walk_stmt(mm, fm, st, held):
    if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return  # separate FuncModels; run with their own (empty) held set
    if isinstance(st, (ast.With, ast.AsyncWith)):
        new = list(held)
        for item in st.items:
            lid = resolve_lock(mm, fm, item.context_expr)
            if lid is not None:
                _note_acquire(fm, tuple(new), lid, item.context_expr)
                new.append(lid)
            else:
                _scan_expr(mm, fm, item.context_expr, tuple(new))
        _walk_body(mm, fm, st.body, tuple(new))
        return
    for field in ("body", "orelse", "finalbody"):
        sub = getattr(st, field, None)
        if sub:
            _walk_body(mm, fm, sub, held)
    for hdl in getattr(st, "handlers", []) or []:
        _walk_body(mm, fm, hdl.body, held)
    if isinstance(st, (ast.If, ast.While)):
        _scan_expr(mm, fm, st.test, held)
    elif isinstance(st, (ast.For, ast.AsyncFor)):
        _scan_expr(mm, fm, st.iter, held)
    elif isinstance(st, (ast.Return, ast.Expr)) and st.value is not None:
        _scan_expr(mm, fm, st.value, held)
    elif isinstance(st, ast.Assign):
        _scan_expr(mm, fm, st.value, held)
        for t in st.targets:
            _note_write(mm, fm, t, st, held, st.value)
    elif isinstance(st, ast.AugAssign):
        _scan_expr(mm, fm, st.value, held)
        _note_write(mm, fm, st.target, st, held, None)
    elif isinstance(st, ast.AnnAssign) and st.value is not None:
        _scan_expr(mm, fm, st.value, held)
        _note_write(mm, fm, st.target, st, held, st.value)
    elif isinstance(st, (ast.Assert, ast.Raise, ast.Delete)):
        for n in ast.iter_child_nodes(st):
            _scan_expr(mm, fm, n, held)


def _note_write(mm, fm, target, st, held, value):
    if _lock_factory(value, mm.aliases):
        return  # installing the sync primitive itself
    base = target
    while isinstance(base, (ast.Subscript, ast.Attribute)) and not (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)):
        base = base.value
    if isinstance(base, ast.Attribute) and isinstance(base.value, ast.Name):
        if base.value.id == "self" and fm.class_name:
            last = fm.qualname.rsplit(".", 1)[-1]
            if last in ("__init__", "__new__", "__del__"):
                return  # happens-before any thread start / after join
            fm.writes.append(WriteRec(
                ("attr", fm.class_name, base.attr), st, held))
            return
        name, direct = base.value.id, False
    elif isinstance(base, ast.Name):
        name, direct = base.id, isinstance(target, ast.Name)
    else:
        return
    if direct and name not in fm.global_decls:
        return  # plain NAME = … without ``global`` is a local bind
    if name in fm.global_decls or (
            not direct and name not in fm.local_names
            and name in mm.global_names):
        fm.writes.append(WriteRec(("global", name), st, held))
        return
    if not direct and name not in fm.local_names:
        # Mutation through a closure cell of an enclosing function.
        outer = fm.qualname
        while "." in outer:
            outer = outer.rsplit(".", 1)[0]
            f = mm.funcs.get(outer)
            if f is None:
                break
            if name in f.local_names:
                fm.writes.append(WriteRec(
                    ("closure", outer, name), st, held))
                return


def _scan_expr(mm, fm, expr, held):
    if expr is None:
        return
    for n in ast.walk(expr):
        if not isinstance(n, ast.Call):
            continue
        d = dotted(n.func, mm.aliases)
        if isinstance(n.func, ast.Name):
            fm.calls.append(CallRec(
                ("name", n.func.id), n, held, dotted=d))
        elif isinstance(n.func, ast.Attribute):
            f = n.func
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and fm.class_name:
                fm.calls.append(CallRec(
                    ("self", fm.class_name, f.attr), n, held,
                    dotted=d, attr=f.attr))
            else:
                fm.calls.append(CallRec(
                    ("dotted", d) if d else ("attr", f.attr), n, held,
                    dotted=d, attr=f.attr,
                    recv_lock=resolve_lock(mm, fm, f.value)))


# -- project phase --------------------------------------------------------


class Project:
    """Cross-module topology: call graph, summaries, order edges, reach."""

    def __init__(self, modules):
        self.mods = {}
        for m in modules:
            tree = getattr(m, "tree", None)
            if tree is None:
                continue
            self.mods[m.path] = ModuleModel(m.path, tree)
        self.by_dotted = {mm.dotted: mm for mm in self.mods.values()}
        self.lock_defs = {}
        self.extern = {}          # "extern::dotted" -> lock id | None
        for mm in self.mods.values():
            self.lock_defs.update(mm.locks)
        self._resolve_externs()
        self.callees = self._call_graph()
        self.summaries = self._fixpoint_summaries()
        self.edges = self._order_edges()
        self.reach = self._reachability()

    # extern lock refs ----------------------------------------------------

    def _extern_lock(self, ref):
        if ref in self.extern:
            return self.extern[ref]
        d = ref[len("extern::"):]
        out = None
        if "." in d:
            mod_part, attr = d.rsplit(".", 1)
            mm = self.by_dotted.get(mod_part)
            if mm is not None:
                out = mm.global_locks.get(attr)
        self.extern[ref] = out
        return out

    def _resolve_externs(self):
        def fix_held(held):
            out = []
            for h in held:
                if h.startswith("extern::"):
                    h = self._extern_lock(h)
                if h is not None:
                    out.append(h)
            return tuple(out)

        for mm in self.mods.values():
            for fm in mm.funcs.values():
                fm.direct_locks = set(fix_held(fm.direct_locks))
                fm.edges = [(a2, b2, n)
                            for a, b, n in fm.edges
                            for a2 in fix_held((a,))
                            for b2 in fix_held((b,))]
                for c in fm.calls:
                    c.held = fix_held(c.held)
                    if c.recv_lock and c.recv_lock.startswith("extern::"):
                        c.recv_lock = self._extern_lock(c.recv_lock)
                for w in fm.writes:
                    w.held = fix_held(w.held)

    # call graph ----------------------------------------------------------

    def resolve_call(self, mm, spec):
        """Call spec -> list of (path, qualname) targets."""
        if spec is None:
            return []
        kind = spec[0]
        if kind == "qual":
            return [(mm.path, spec[1])] if spec[1] in mm.funcs else []
        if kind == "name":
            name = spec[1]
            return [(mm.path, q) for q, f in mm.funcs.items()
                    if (q == name or q.endswith("." + name))
                    and not f.is_method]
        if kind == "self":
            _, cls, meth = spec
            q = f"{cls}.{meth}"
            return [(mm.path, q)] if q in mm.funcs else []
        if kind == "selfattr":
            meth = spec[1]
            return [(mm.path, q) for q, f in mm.funcs.items()
                    if f.is_method and q.endswith("." + meth)]
        if kind == "dotted":
            d = spec[1]
            if d is None or "." not in d:
                return []
            mod_part, name = d.rsplit(".", 1)
            target = self.by_dotted.get(mod_part)
            if target is None:
                return []
            if name in target.funcs:
                return [(target.path, name)]
            # One re-export hop through a package __init__.
            src = target.reexports.get(name)
            if src and "." in src:
                m2, n2 = src.rsplit(".", 1)
                t2 = self.by_dotted.get(m2)
                if t2 is not None and n2 in t2.funcs:
                    return [(t2.path, n2)]
            return []
        return []

    def _call_graph(self):
        callees = {}
        for mm in self.mods.values():
            for q, fm in mm.funcs.items():
                out = set()
                for c in fm.calls:
                    out.update(self.resolve_call(mm, c.spec))
                callees[(mm.path, q)] = out
        return callees

    # may-acquire summaries ----------------------------------------------

    def _fixpoint_summaries(self):
        summaries = {}
        for mm in self.mods.values():
            for q, fm in mm.funcs.items():
                summaries[(mm.path, q)] = set(fm.direct_locks)
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed, rounds = False, rounds + 1
            for fkey, targets in self.callees.items():
                s = summaries[fkey]
                for t in targets:
                    extra = summaries.get(t, ()) - s
                    if extra:
                        s.update(extra)
                        changed = True
        return summaries

    # lock-order edges ----------------------------------------------------

    def _order_edges(self):
        edges = {}
        for mm in self.mods.values():
            for q, fm in mm.funcs.items():
                for a, b, node in fm.edges:
                    if a != b:
                        edges.setdefault((a, b), (mm.path, node))
                for c in fm.calls:
                    if not c.held:
                        continue
                    for t in self.resolve_call(mm, c.spec):
                        for b in self.summaries.get(t, ()):
                            for a in c.held:
                                if a != b:
                                    edges.setdefault((a, b),
                                                     (mm.path, c.node))
        return edges

    def cycles(self):
        """SCCs of size >= 2 in the lock-order graph, sorted."""
        adj = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        index, low, onstk = {}, {}, set()
        stack, out, counter = [], [], [0]

        def strong(v):
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            onstk.add(v)
            for w in sorted(adj.get(v, ())):
                if w not in index:
                    strong(w)
                    low[v] = min(low[v], low[w])
                elif w in onstk:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    onstk.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    out.append(sorted(scc))
        for v in sorted(adj):
            if v not in index:
                strong(v)
        return sorted(out)

    # thread reachability -------------------------------------------------

    def _root_seeds(self, mm, root):
        return self.resolve_call(mm, root.target)

    def _reachability(self):
        """fkey -> set of root keys that can execute it."""
        reach = {}

        def bfs(seeds, key):
            todo = list(seeds)
            seen = set()
            while todo:
                f = todo.pop()
                if f in seen or f not in self.callees:
                    continue
                seen.add(f)
                reach.setdefault(f, set()).add(key)
                todo.extend(self.callees[f])

        main_seeds = []
        for mm in self.mods.values():
            for q, fm in mm.funcs.items():
                base = q.split(".", 1)[0]
                # Only module-level functions and methods are externally
                # callable; nested defs/lambdas reach a root solely via
                # resolvable calls or thread targets.
                top_level = ("." not in q and not q.startswith("<lambda>")) \
                    or fm.is_method
                if top_level and (fm.public or base in mm.toplevel_called
                                  or q in mm.toplevel_called):
                    main_seeds.append((mm.path, q))
        bfs(main_seeds, MAIN_ROOT)
        for mm in self.mods.values():
            for root in mm.roots:
                bfs(self._root_seeds(mm, root), root.key)
        return reach

    def roots_of(self, path, qualname):
        return self.reach.get((path, qualname), set())

    def root_by_key(self, key):
        for mm in self.mods.values():
            for r in mm.roots:
                if r.key == key:
                    return r
        return None

    # shared-state census -------------------------------------------------

    def shared_writes(self):
        """{(path-scoped object key): [(fkey, WriteRec)]}."""
        objs = {}
        for mm in self.mods.values():
            for q, fm in mm.funcs.items():
                for w in fm.writes:
                    key = (w.obj[0], mm.path) + w.obj[1:]
                    objs.setdefault(key, []).append(((mm.path, q), w))
        return objs


# -- lockwatch reconciliation model ---------------------------------------


def build_project(modules):
    return Project(modules)


def build_lock_model(paths):
    """Static lock model for obs/lockwatch.reconcile: lock census keyed
    by creation site + the C201 order edges. Pure data (JSON-able)."""
    from flake16_framework_tpu.analysis import engine as eng

    mods = [eng.Module(f) for f in eng.iter_py_files(paths)]
    proj = Project([m for m in mods if m.tree is not None])
    return {
        "locks": {lid: {"site": ld.site, "kind": ld.kind}
                  for lid, ld in sorted(proj.lock_defs.items())},
        "edges": sorted([a, b] for (a, b) in proj.edges),
    }


def transitive_closure(edges):
    """{a: set of ids reachable from a} over [a, b] pairs."""
    adj = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    closure = {}
    for a in adj:
        seen, todo = set(), [a]
        while todo:
            v = todo.pop()
            for w in adj.get(v, ()):
                if w not in seen:
                    seen.add(w)
                    todo.append(w)
        closure[a] = seen
    return closure


def find_edge_cycle(edges):
    """One cycle (as a list of nodes) in [a, b] pairs, or None."""
    adj = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    WHITE, GREY, BLACK = 0, 1, 2
    color = {v: WHITE for v in adj}
    parent = {}

    def dfs(v):
        color[v] = GREY
        for w in sorted(adj.get(v, ())):
            if color.get(w, WHITE) == WHITE:
                parent[w] = v
                hit = dfs(w)
                if hit:
                    return hit
            elif color.get(w) == GREY:
                cyc, cur = [w], v
                while cur != w:
                    cyc.append(cur)
                    cur = parent[cur]
                cyc.reverse()
                return cyc
        color[v] = BLACK
        return None

    for v in sorted(adj):
        if color[v] == WHITE:
            hit = dfs(v)
            if hit:
                return hit
    return None
