"""Rule pack 4 — f16audit IR rules (I-rules, ISSUE 13).

The static half of the first-silicon story: every contract the
unattended TPU session relies on, proven on the CPU host in seconds by
tracing the REAL entry points (analysis/ir.py) instead of reading
source text. The pack registers its catalog with the lint engine (so
``--rules``, baselines and fingerprints know the I-ids) but contributes
no ``check_module``/``check_project`` — IR findings only come from the
audit entry points (``audit`` verb / ``lint --ir``), because tracing
requires jax and plain lint must not (test_analysis_never_imports_jax).

Rules:

- I101 (error): host-callback primitive (pure_callback / io_callback /
  debug_callback) in a jit-reachable program — one host round-trip per
  dispatch, the tunnel tax f16lint J101 only guesses at.
- I102 (warning): the IR found a host callback that the J101 AST taint
  heuristic did NOT flag in the entry's defining module — the
  heuristic's ground-truth cross-check.
- I201 (error): nondeterministic primitive (lax.rng_uniform) — breaks
  the write-ahead journal's bit-identical resume contract.
- I202 (error): 64-bit aval in the traced program — f64 promotion
  drift; downcasts silently with x64 off, breaks bit-identical resume
  with it on.
- I301 (error): static dispatch census (#plans for the full grid,
  parallel/planner.py) disagrees with the runtime
  ``grid_dispatch_count`` — or, for the planner's SHAP arm (ISSUE 14),
  ``shap_dispatch_count`` — the bench recorded: the planner's
  one-program-per-family contract no longer holds.
- I401 (error): a plan's peak-memory envelope (ir.peak_live_bytes)
  exceeds the device budget (``F16_DEVICE_BUDGET_MB``) — the run would
  OOM on silicon; refused pre-flight.
- I501 (error): shard_map config-axis violation — an input/output
  replicated over "config" or a collective gathering across it.

Module-import contract: NOTHING here imports jax at module level; the
finding builders import analysis/ir.py lazily.
"""

import glob
import json
import os

from flake16_framework_tpu.analysis.engine import (
    ERROR, WARNING, Finding, RuleInfo,
)

PACK_NAME = "ir"

RULES = {r.id: r for r in (
    RuleInfo("I101", ERROR,
             "host-callback primitive in a traced program — one"
             " device->host round-trip per dispatch"),
    RuleInfo("I102", WARNING,
             "IR ground truth found a host callback the J101 AST taint"
             " heuristic missed in the defining module"),
    RuleInfo("I201", ERROR,
             "nondeterministic primitive in a traced program — breaks"
             " bit-identical journal resume"),
    RuleInfo("I202", ERROR,
             "64-bit aval in a traced program — f64 promotion drift"
             " under the x64-off sweep contract"),
    RuleInfo("I301", ERROR,
             "static dispatch census != runtime grid_dispatch_count —"
             " the one-program-per-family planner contract drifted"),
    RuleInfo("I401", ERROR,
             "plan peak-memory envelope exceeds the device budget"
             " (F16_DEVICE_BUDGET_MB) — would OOM; refused pre-flight"),
    RuleInfo("I501", ERROR,
             "shard_map config-axis sharding violation — replication or"
             " collective gather across independent plan members"),
)}

# Where each traced entry's program is DEFINED — findings anchor there
# so they are actionable in an editor, with the entry named in the
# message and the fingerprint keyed on the entry (stable snippet).
_SWEEP_PATH = "flake16_framework_tpu/parallel/sweep.py"
_SERVE_PATH = "flake16_framework_tpu/serve/store.py"
_SHAP_PATH = "flake16_framework_tpu/ops/treeshap.py"


def _finding(rule_id, message, *, path, entry):
    return Finding(rule_id, RULES[rule_id].severity, path, 0, 0,
                   message, snippet=entry)


# -- I3: static dispatch census (pure host, no jax) ---------------------


def static_plans(*, n=120, n_folds=10, devices=1, tree_overrides=None):
    """The full grid's execution plans — the static dispatch census is
    ``len()`` of this. Host-only: planner and config import no jax, so
    the census is printable on a machine with no backend at all."""
    from flake16_framework_tpu import config as cfg
    from flake16_framework_tpu.parallel import planner

    return planner.plan_grid(
        list(cfg.iter_config_keys()), devices=devices, n=n,
        n_folds=n_folds, tree_overrides=tree_overrides)


def latest_bench_census(repo=None, metric="grid_dispatch_count"):
    """(runtime dispatch count, grid_plans, grid_configs, path) for
    ``metric`` from the NEWEST committed BENCH_r*.json that carries it
    (grid_dispatch_count from BENCH_r08, shap_dispatch_count from
    BENCH_r09), or None when no record does."""
    repo = repo or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    best = None
    for p in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        try:
            with open(p) as fd:
                obj = json.load(fd)
        except (OSError, ValueError):
            continue
        # Committed records wrap the final metric under "parsed"
        # (tools/recovery_watch.persist_bench_json); a raw bench line
        # carries "detail" at top level — accept both.
        parsed = obj.get("parsed") if isinstance(obj.get("parsed"),
                                                 dict) else obj
        detail = (parsed.get("detail") or {}) if isinstance(parsed,
                                                            dict) else {}
        count = detail.get(metric)
        if isinstance(count, (int, float)):
            best = (int(count), detail.get("grid_plans"),
                    detail.get("grid_configs"), os.path.basename(p))
    return best


def census_findings(plans=None, *, repo=None, runtime_count=None):
    """I301: reconcile the static census against the runtime one.

    ``runtime_count`` (when given, e.g. by bench.py's live audit stage)
    wins; otherwise the newest committed BENCH_r*.json census is used.
    Reconciliation semantics: the comparison only binds when the bench
    record measured the SAME grid (its ``grid_configs`` equals the
    current grid size) — a record predating a grid change is stale
    evidence, reported as no finding (the next bench re-records it)."""
    from flake16_framework_tpu import config as cfg

    plans = static_plans() if plans is None else plans
    static_n = len(plans)
    grid_size = len(list(cfg.iter_config_keys()))
    source = "caller"
    if runtime_count is None:
        rec = latest_bench_census(repo)
        if rec is None:
            return [], {"static": static_n, "runtime": None,
                        "source": None, "match": None}
        runtime_count, _plans_rec, rec_grid, source = rec
        if rec_grid is not None and int(rec_grid) != grid_size:
            return [], {"static": static_n, "runtime": int(runtime_count),
                        "source": source, "match": None,
                        "stale": f"bench measured a {rec_grid}-config "
                                 f"grid; current grid is {grid_size}"}
    findings = []
    if int(runtime_count) != static_n:
        findings.append(_finding(
            "I301",
            f"static dispatch census is {static_n} plan(s) for the "
            f"{grid_size}-config grid but the runtime census "
            f"({source}) measured {int(runtime_count)} dispatches — "
            "the one-program-per-family contract drifted",
            path=_SWEEP_PATH, entry="grid_dispatch_count"))
    return findings, {"static": static_n, "runtime": int(runtime_count),
                      "source": source,
                      "match": int(runtime_count) == static_n}


def shap_census_findings(plans=None, *, repo=None, runtime_count=None):
    """I301 for the SHAP arm (ISSUE 14): the planner groups the explain
    grid exactly like the fit grid (plan_explain_grid delegates to
    plan_grid), so the static SHAP census IS #plans; the runtime side is
    bench's ``shap_dispatch_count`` (the dispatch_stats delta around the
    warm whole-grid shap_grid pass). Same staleness rule as the fit
    census: a record measured on a different grid size binds nothing."""
    from flake16_framework_tpu import config as cfg

    plans = static_plans() if plans is None else plans
    static_n = len(plans)
    grid_size = len(list(cfg.iter_config_keys()))
    source = "caller"
    if runtime_count is None:
        rec = latest_bench_census(repo, metric="shap_dispatch_count")
        if rec is None:
            return [], {"static": static_n, "runtime": None,
                        "source": None, "match": None}
        runtime_count, _plans_rec, rec_grid, source = rec
        if rec_grid is not None and int(rec_grid) != grid_size:
            return [], {"static": static_n, "runtime": int(runtime_count),
                        "source": source, "match": None,
                        "stale": f"bench measured a {rec_grid}-config "
                                 f"grid; current grid is {grid_size}"}
    findings = []
    if int(runtime_count) != static_n:
        findings.append(_finding(
            "I301",
            f"static SHAP dispatch census is {static_n} plan(s) for the "
            f"{grid_size}-config grid but the runtime census "
            f"({source}) measured {int(runtime_count)} dispatches — "
            "the one-explain-program-per-family contract drifted",
            path=_SWEEP_PATH, entry="shap_dispatch_count"))
    return findings, {"static": static_n, "runtime": int(runtime_count),
                      "source": source,
                      "match": int(runtime_count) == static_n}


# -- per-program walkers -> findings ------------------------------------


def program_findings(entry, closed, *, path):
    """I101/I201/I202 findings for one traced program."""
    from flake16_framework_tpu.analysis import ir

    findings = []
    for prim in ir.callback_sites(closed):
        findings.append(_finding(
            "I101", f"traced program {entry!r} contains host-callback "
            f"primitive {prim!r} — a device->host round-trip per "
            "dispatch", path=path, entry=f"{entry}:{prim}"))
    for prim in ir.nondet_sites(closed):
        findings.append(_finding(
            "I201", f"traced program {entry!r} contains "
            f"nondeterministic primitive {prim!r} — journal resume "
            "would not be bit-identical", path=path,
            entry=f"{entry}:{prim}"))
    for prim, dtype in ir.wide_dtype_sites(closed):
        findings.append(_finding(
            "I202", f"traced program {entry!r}: {prim} produces a "
            f"{dtype} value — 64-bit drift under the x64-off contract",
            path=path, entry=f"{entry}:{prim}:{dtype}"))
    return findings


def sharding_findings(entry, closed, *, path=_SWEEP_PATH, axis="config"):
    """I501 findings for one traced mesh program."""
    from flake16_framework_tpu.analysis import ir

    n_maps, problems = ir.shard_map_audit(closed, axis=axis)
    findings = []
    if n_maps == 0:
        findings.append(_finding(
            "I501", f"traced mesh program {entry!r} contains no "
            "shard_map — the config axis is not sharded at all",
            path=path, entry=f"{entry}:no-shard_map"))
    for prob in problems:
        findings.append(_finding(
            "I501", f"traced mesh program {entry!r}: {prob}",
            path=path, entry=f"{entry}:{prob[:48]}"))
    return findings


def budget_findings(entry, envelope, *, budget_mb, path=_SWEEP_PATH):
    """I401 finding when one program's envelope exceeds the budget."""
    if not budget_mb or budget_mb <= 0:
        return []
    peak_mb = envelope["peak_bytes"] / 2**20
    if peak_mb <= budget_mb:
        return []
    return [_finding(
        "I401", f"plan program {entry!r} peak-memory envelope "
        f"{peak_mb:.1f} MB exceeds the device budget {budget_mb:g} MB "
        "(F16_DEVICE_BUDGET_MB) — would OOM on dispatch",
        path=path, entry=f"{entry}:budget")]


def crosscheck_findings(entry, closed, *, source_path):
    """I102: the J101 taint heuristic's ground-truth cross-check. When
    the IR proves a host callback exists in ``entry`` but the AST pack
    raises no J101-family finding in the program's defining module, the
    heuristic has a blind spot worth a warning (the reverse direction —
    AST flags, IR clean — is already a hard lint failure and cannot
    coexist with a green gate)."""
    from flake16_framework_tpu.analysis import ir
    from flake16_framework_tpu.analysis import rules_jax
    from flake16_framework_tpu.analysis.engine import Module

    prims = ir.callback_sites(closed)
    if not prims:
        return []
    try:
        ast_findings = rules_jax.check_module(Module(source_path))
    except OSError:
        return []
    taint_rules = {"J101", "J102", "J103", "J104"}
    if any(f.rule in taint_rules for f in ast_findings):
        return []
    return [_finding(
        "I102", f"IR ground truth: {entry!r} reaches host callback(s) "
        f"{prims} but the J101 taint heuristic reports nothing in "
        f"{source_path} — heuristic blind spot", path=source_path,
        entry=f"{entry}:crosscheck")]


# -- the whole audit ----------------------------------------------------


def run_audit(*, n=120, n_trees=2, n_folds=10, n_projects=26,
              max_depth=8, n_explain=16, budget_mb=None, repo=None,
              mesh=True, runtime_count=None, runtime_shap_count=None):
    """Trace every real entry point and run every I-rule. Returns
    (findings, info): ``info`` carries the census reconciliations (fit
    AND shap arms), the per-plan memory-envelope table (the ``prof_fit
    --audit`` payload) and the traced-entry list. Shape defaults mirror
    the bench's dispatch-census stage (n=120, trees=2, max_depth=8,
    explain=16) so the static and runtime censuses describe the same
    programs."""
    from flake16_framework_tpu.analysis import ir

    if budget_mb is None:
        raw = os.environ.get("F16_DEVICE_BUDGET_MB", "")
        budget_mb = float(raw) if raw else None

    tree_overrides = {"Random Forest": n_trees, "Extra Trees": n_trees}
    plans = static_plans(n=n, n_folds=n_folds,
                         tree_overrides=tree_overrides)
    findings, census = census_findings(plans, repo=repo,
                                       runtime_count=runtime_count)
    shap_findings, shap_census = shap_census_findings(
        plans, repo=repo, runtime_count=runtime_shap_count)
    findings.extend(shap_findings)
    info = {"census": census, "shap_census": shap_census,
            "envelopes": [], "entries": []}

    def one(entry, closed, *, path, source_path=None, envelope=False,
            batch=None):
        info["entries"].append(entry)
        findings.extend(program_findings(entry, closed, path=path))
        findings.extend(crosscheck_findings(
            entry, closed, source_path=source_path or path))
        if envelope:
            env = ir.memory_envelope(closed)
            env.update(entry=entry, batch=batch,
                       peak_mb=round(env["peak_bytes"] / 2**20, 2))
            info["envelopes"].append(env)
            findings.extend(budget_findings(entry, env,
                                            budget_mb=budget_mb))

    for pl in plans:
        entry = f"scores.plan_batch[{'/'.join(pl.family)}]"
        closed = ir.trace_plan_program(pl, mesh=None,
                                       n_projects=n_projects,
                                       max_depth=max_depth)
        one(entry, closed, path=_SWEEP_PATH, envelope=True,
            batch=pl.batch)

    # The planner's SHAP arm (ISSUE 14): one fused explain program per
    # family, plus both beyond-paper modes on the first family (the mode
    # engines are family-independent; one trace each proves the I1/I2
    # contracts without tripling the audit wall).
    from flake16_framework_tpu import config as cfg
    from flake16_framework_tpu.parallel import planner as _planner

    shap_plans = _planner.plan_explain_grid(
        list(cfg.iter_config_keys()), devices=1, n=n, n_folds=n_folds,
        n_explain=n_explain, tree_overrides=tree_overrides)
    for pl in shap_plans:
        entry = f"shap.plan_batch[{'/'.join(pl.family)}]"
        closed = ir.trace_shap_plan_program(pl, mesh=None,
                                            max_depth=max_depth)
        one(entry, closed, path=_SWEEP_PATH, envelope=True,
            batch=pl.batch)
    for mode in ("interventional", "interaction"):
        pl = shap_plans[0]
        entry = f"shap.plan_batch.{mode}[{'/'.join(pl.family)}]"
        closed = ir.trace_shap_plan_program(pl, mesh=None,
                                            max_depth=max_depth,
                                            mode=mode)
        one(entry, closed, path=_SWEEP_PATH, envelope=True,
            batch=pl.batch)

    if mesh:
        amesh = ir.audit_mesh()
        for pl in plans:
            entry = f"scores.plan_batch.mesh[{'/'.join(pl.family)}]"
            closed = ir.trace_plan_program(pl, mesh=amesh,
                                           n_projects=n_projects,
                                           max_depth=max_depth)
            info["entries"].append(entry)
            findings.extend(program_findings(entry, closed,
                                             path=_SWEEP_PATH))
            findings.extend(sharding_findings(entry, closed))

    serve = serve_entries(n_trees=max(n_trees, 2))
    for entry, (fn, args, kwargs) in serve.items():
        closed = ir.trace_entry(fn, args, kwargs)
        one(entry, closed, path=_SERVE_PATH)

    for entry, (fn, args, kwargs) in ir.shap_kernel_entries(
            n_trees=max(n_trees, 2), depth=max_depth).items():
        closed = ir.trace_entry(fn, args, kwargs)
        one(entry, closed, path=_SHAP_PATH)

    findings.sort(key=lambda f: (f.rule, f.path, f.snippet))
    info["budget_mb"] = budget_mb
    return findings, info


def serve_entries(*, n_trees=2, max_nodes=64, n_cols=16, bucket=32,
                  depth=8):
    """The serving layer's AOT entry points as abstract (fn, args,
    kwargs) handles (serve/store.ExecutableStore.audit_handles) — what
    every live request dispatches through, traced without a registry or
    a compile."""
    from flake16_framework_tpu.serve.store import ExecutableStore

    store = ExecutableStore(None)
    return store.audit_handles(n_trees=n_trees, max_nodes=max_nodes,
                               n_cols=n_cols, bucket=bucket, depth=depth)
