"""CLI — the reference's seven verbs (component 1,
/root/reference/experiment.py:693-714), same names and stage contracts:

    python -m flake16_framework_tpu setup       # provision subject venvs
    python -m flake16_framework_tpu container NAME CMD...   # in-container
    python -m flake16_framework_tpu run MODE... # docker collection campaign
    python -m flake16_framework_tpu tests       # collate -> tests.json
    python -m flake16_framework_tpu scores      # TPU sweep -> scores.pkl
    python -m flake16_framework_tpu shap        # TPU Tree SHAP -> shap.pkl
    python -m flake16_framework_tpu figures     # LaTeX artifacts

plus extension verbs the reference lacks:

    python -m flake16_framework_tpu shap grid|interventional|interaction
        [explain=N] [background=N]
        # whole-216-grid SHAP through the planner's fused explain
        # programs (<= #families + O(1) dispatches; pipeline.shap_grid):
        # path-dependent values, interventional values vs a background
        # set, or SHAP interaction values [F, F] per sample
    python -m flake16_framework_tpu report [RUN_DIR] [--json] [--attrib]
        # render a telemetry run (F16_TELEMETRY=1 during scores/shap/bench)
        # into per-stage compile/execute walls, throughput, memory peaks;
        # --attrib ranks hot configs/stages and joins kernel costs
    python -m flake16_framework_tpu trace [RUN_DIR] [--out FILE]
        # convert a telemetry run into Chrome-trace/Perfetto JSON
        # (obs/trace.py; load in chrome://tracing or ui.perfetto.dev)
    python -m flake16_framework_tpu perf backfill|ingest|diff|sentinel|lookup
        # the performance observatory (obs/perfdb.py + obs/perf_diff.py):
        # a persistent CRC'd perf database keyed by (backend, shape,
        # kernel, knob snapshot). `backfill` ingests the committed
        # BENCH_r*.json trajectory; `ingest PATH...` adds bench results,
        # telemetry run dirs, or audit documents; `diff A B` joins two
        # runs/rounds per kernel/stage and ranks the deltas (--perfetto
        # exports a trace-verb-compatible view); `sentinel` fits the
        # whole committed trajectory and flags step-changes with the
        # round and top contributing stages (tier-1-safe after
        # `bench --gate`); `lookup BACKEND SHAPE [KERNEL]` prints the
        # best-known knob row the planner/serve store consult
    python -m flake16_framework_tpu tune [--family FS/Model] [--dry-run]
        [--min-gain PCT] [--no-parity-knobs] [--db PATH]
        # f16tune (perf/tuner.py): bench-in-the-loop autotuner over the
        # declared KnobSpace — successive-halving search per (backend,
        # plan shape, model family) with fresh-subprocess bench probes
        # as the oracle, seeded from committed BENCH history and I401
        # audit envelopes; winners past the gain floor land as `tuned`
        # perfdb rows the planner consults at plan time (absent rows
        # keep today's defaults byte-for-byte). Parity-affecting
        # winners (F16_HIST_BINS) re-run the parity harness before
        # acceptance and only activate via explicit env export
    python -m flake16_framework_tpu lint [PATHS] [--json] [--baseline F]
        # f16lint: JAX/TPU-hygiene static analysis + config-grid
        # pre-flight (analysis/); exit 1 on unsuppressed findings;
        # --ir folds the f16audit IR findings in
    python -m flake16_framework_tpu audit [--json] [--budget-mb MB]
        # f16audit: trace the real entry points (planner family
        # programs, serve AOT executables, SHAP kernels) with abstract
        # inputs and statically prove the dispatch/determinism/memory/
        # sharding contracts (analysis/ir.py, I-rules); reconciles the
        # static dispatch census against the benched
        # grid_dispatch_count and prints per-plan memory envelopes
    python -m flake16_framework_tpu bench --gate [RESULT.json]
        # regression gate over the committed BENCH_r*.json trajectory
        # (tools/bench_gate.py); exit 1 naming the regressed metric
    python -m flake16_framework_tpu serve [--ledger scores.pkl] [--json]
        # always-on scoring service (serve/): AOT-warmed predict+SHAP
        # executables, microbatched async queue, model registry; drives
        # a closed-loop client load and prints throughput + p50/p99.
        # SIGTERM (--hold mode) triggers a graceful drain: admission
        # close -> in-flight complete -> queued requests get a
        # RETRIABLE rejection -> registry/AOT-manifest flush, with a
        # --drain-deadline that escalates to checkpoint-and-abort
    python -m flake16_framework_tpu resume [lopo] [fused] [planner]
        [dispatch=N]
        # continue a preempted `scores` sweep from its write-ahead
        # journal (<scores.pkl>.journal; fold-granular, fsync'd):
        # completed configs and folds replay, only unfinished
        # (config, fold) pairs rerun with identical rng keys, so the
        # final pickle is bit-identical to an uninterrupted run.
        # Errors out when no resume state exists

Fault tolerance (resilience/): ``scores`` dispatches every config through
the resilience guard — transient device faults retry with backoff, OOMs
retry at halved chunk bounds, and a config that exhausts its attempts is
QUARANTINED: the sweep finishes the rest, persists everything, writes
``<scores.pkl>.quarantine.json`` (fault class + attempt history), and
exits with code 23 (resilience.QUARANTINE_EXIT_CODE) listing the
quarantined configs. Re-running ``scores`` re-attempts exactly those
configs (they are absent from the pickle, so the per-config resume picks
them up). ``F16_FAULT_INJECT=<config>:<attempt>:<class>[;...]`` injects
deterministic faults for drills; the process classes
``<config>:<fold>:sigkill|sigterm`` kill the process at that fold's
journal-append point for the chaos drill (tools/chaos_drill.py,
resilience/supervisor.py; see PROFILE.md "Fault tolerance" and "Crash
tolerance").

Unknown/missing verbs raise ValueError like the reference.
"""

import sys


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        raise ValueError("No command given")

    command, *args = argv

    if command == "setup":
        from flake16_framework_tpu.runner.containers import provision_all

        provision_all()
    elif command == "container":
        from flake16_framework_tpu.runner.containers import container_entrypoint

        container_entrypoint(*args)
    elif command == "run":
        from flake16_framework_tpu.runner.containers import run_experiment

        run_experiment(args)
    elif command == "tests":
        from flake16_framework_tpu.runner.collate import write_tests

        write_tests()
    elif command == "scores":
        from flake16_framework_tpu.pipeline import write_scores

        # Optional extension verbs the reference CLI lacks: `scores lopo`
        # runs the 26-project leave-one-project-out CV (north star) to
        # scores-lopo.pkl; `scores profile=DIR` captures a jax.profiler trace.
        kw = {}
        for a in args:
            if a == "lopo":
                kw["cv"] = "lopo"  # default out_file follows the cv scheme
            elif a.startswith("profile="):
                kw["profile_dir"] = a.split("=", 1)[1]
            elif a.startswith("dispatch="):
                # bounded fit dispatches (fault-envelope control, see
                # PROFILE.md): trees per dispatch, as in the bench
                kw["dispatch_trees"] = int(a.split("=", 1)[1]) or None
            elif a == "fused":
                # one device dispatch per config/batch (TPU round-trip
                # amortization — SweepEngine fused mode)
                kw["fused"] = True
            elif a == "planner":
                # planner/executor sweep (ISSUE 12): one fused program
                # per model-family plan, whole grid in <= #families +
                # O(1) dispatches (parallel/planner.py)
                kw["planner"] = True
            else:
                raise ValueError(f"Unrecognized scores option {a!r}")
        write_scores(**kw)
    elif command == "resume":
        # Preemption recovery (ISSUE 11): the same sweep as `scores`,
        # but it REQUIRES on-disk resume state — a write-ahead journal
        # (<scores.pkl>.journal) and/or a partial scores pickle — so a
        # typo'd invocation can never silently start from scratch. The
        # journal replay summary prints before the sweep continues.
        import os

        from flake16_framework_tpu.constants import (
            LOPO_SCORES_FILE, SCORES_FILE,
        )
        from flake16_framework_tpu.pipeline import write_scores
        from flake16_framework_tpu.resilience import journal as rjournal

        kw = {}
        for a in args:
            if a == "lopo":
                kw["cv"] = "lopo"
            elif a.startswith("profile="):
                kw["profile_dir"] = a.split("=", 1)[1]
            elif a.startswith("dispatch="):
                kw["dispatch_trees"] = int(a.split("=", 1)[1]) or None
            elif a == "fused":
                kw["fused"] = True
            elif a == "planner":
                kw["planner"] = True
            else:
                raise ValueError(f"Unrecognized resume option {a!r}")
        out_file = (LOPO_SCORES_FILE if kw.get("cv") == "lopo"
                    else SCORES_FILE)
        jpath = rjournal.journal_path(out_file)
        if not (os.path.exists(jpath) or os.path.exists(out_file)):
            raise ValueError(
                f"resume: no resume state — neither {jpath} nor "
                f"{out_file} exists (run `scores` for a fresh sweep)")
        write_scores(**kw)
    elif command == "shap":
        # Bare `shap` is the paper artifact (two reference configs ->
        # shap.pkl, unchanged). Extension modes (ISSUE 14) run the WHOLE
        # 216 grid through the planner's fused explain programs
        # (pipeline.shap_grid, <= #families + O(1) dispatches):
        #   shap grid           path-dependent Tree SHAP   -> shap-grid.pkl
        #   shap interventional vs a background set        -> shap-interventional.pkl
        #   shap interaction    interaction values [F, F]  -> shap-interaction.pkl
        # with explain=N / background=N sizing the explain + background
        # row counts (defaults 64 / 32).
        mode = None
        kw = {}
        for a in args:
            if a in ("grid", "interventional", "interaction"):
                if mode is not None:
                    raise ValueError("shap: give at most one mode")
                mode = a
            elif a.startswith("explain="):
                kw["n_explain"] = int(a.split("=", 1)[1])
            elif a.startswith("background="):
                kw["n_background"] = int(a.split("=", 1)[1])
            else:
                raise ValueError(f"Unrecognized shap option {a!r}")
        if mode is None:
            if kw:
                raise ValueError(
                    "shap: explain=/background= need a mode "
                    "(grid|interventional|interaction)")
            from flake16_framework_tpu.pipeline import write_shap

            write_shap()
        else:
            from flake16_framework_tpu.pipeline import shap_grid

            engine_mode = "path" if mode == "grid" else mode
            shap_grid(out_file=f"shap-{mode}.pkl", mode=engine_mode, **kw)
    elif command == "figures":
        from flake16_framework_tpu.figures.report import write_figures

        write_figures()
    elif command == "report":
        from flake16_framework_tpu.obs.report import report_main

        report_main(args)
    elif command == "trace":
        from flake16_framework_tpu.obs.trace import trace_main

        trace_main(args)
    elif command == "perf":
        from flake16_framework_tpu.obs.perf_diff import perf_main

        perf_main(args)
    elif command == "tune":
        from flake16_framework_tpu.perf.tuner import tune_main

        code = tune_main(args)
        if code:
            raise SystemExit(code)
    elif command == "bench":
        # Only the gate lives behind the verb; the measurement harness
        # stays the standalone bench.py (it owns its env/backend setup).
        if not args or args[0] != "--gate":
            raise ValueError(
                "bench verb supports only --gate (run bench.py directly "
                "for measurements)")
        import os

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        from bench_gate import gate_main

        code = gate_main(args[1:])
        if code:
            raise SystemExit(code)
    elif command == "serve":
        from flake16_framework_tpu.serve.cli import serve_main

        code = serve_main(args)
        if code:
            raise SystemExit(code)
    elif command == "lint":
        from flake16_framework_tpu.analysis.cli import lint_main

        code = lint_main(args)
        if code:
            raise SystemExit(code)
    elif command == "audit":
        from flake16_framework_tpu.analysis.cli import audit_main

        code = audit_main(args)
        if code:
            raise SystemExit(code)
    else:
        raise ValueError("Unrecognized command given")


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:
        # `lint | head` etc. — the reader went away; swap stdout for
        # devnull so interpreter shutdown doesn't re-raise on flush.
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        raise SystemExit(1)
