"""f16tune — the bench-in-the-loop kernel autotuner (ISSUE 20 tentpole).

PROFILE.md's ledger shows the hist grower's knob optima flipping with
shape (node-batch width 8 at N=400 vs 16 at N=1000), and nobody searches
the knob space by hand — so fixed constants provably leave wall-clock on
the table, the same shape-dependent tuning problem XGBoost's GPU work
(arXiv 1806.11248) and GPUTreeShap's bin-packing (arXiv 2010.13972)
solved per workload. This module closes the loop:

- **KnobSpace** — the typed registry of every tunable: env var, value
  domain, shape-applicability predicate, and the results-neutral vs
  parity-affecting flag. f16lint G108 audits kernel-path constants
  against this registry (a tunable constant without a registration is a
  finding), and parity-affecting winners must re-pass the parity harness
  before acceptance.
- **Search** — per (backend, plan-shape, model family): successive
  halving over short bench probes (each candidate runs the REAL engine
  on the real bench configs in a fresh subprocess — the hist knobs are
  import-frozen by design), repetitions doubling as the field halves,
  then a compose rung that merges each knob's best value. Seeding comes
  from the perfdb: committed BENCH history sizes the probe timeout and
  baseline expectation, and I401 audit memory envelopes veto widths
  whose scaled working set would blow the memory cap.
- **Persistence** — winners land as ``tuned`` perfdb rows keyed
  per-model (obs/perfdb.model_kernel: plan shapes collide across RF/ET)
  that ``plan_lookup``/``tuned_fit_overrides`` already consult at plan
  time. Absent rows keep execution byte-for-byte today's defaults;
  parity-affecting winners are recorded but only take effect when their
  env is exported explicitly (tools/recovery_watch.py bench_tuned) —
  the plan-time consult applies results-neutral knobs only, so the
  journal-resume/per-config paths can never diverge from a plan.

Import-light on purpose: no jax/bench import at module load — the lint
census, tests, and ``--dry-run`` never touch a device.
"""

import json
import os
import subprocess
import sys
import time
from collections import namedtuple

from flake16_framework_tpu.obs import perfdb
from flake16_framework_tpu.parallel import planner

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ENSEMBLES = ("Random Forest", "Extra Trees")

# One registered tunable. ``domain`` values are env-var STRINGS (the
# knob's transport is the environment); ``applies(shape, backend,
# model)`` gates candidates per (n, n_feat, n_trees, n_folds, cap) plan
# shape; ``parity_affecting`` knobs change model outputs and re-run the
# parity harness before a winner is accepted (results-neutral knobs grow
# bit-identical forests by the grower contract and skip it).
Knob = namedtuple(
    "Knob", ["name", "domain", "default", "parity_affecting", "target",
             "applies", "note"])


def _hist_families(shape, backend, model):
    return model in ENSEMBLES


def _cpu_hist(shape, backend, model):
    return backend == "cpu" and model in ENSEMBLES


def _device_hist(shape, backend, model):
    return backend != "cpu" and model in ENSEMBLES


def _refine_families(shape, backend, model):
    # In-step exact refinement runs for non-random-split ensembles only
    # (ET thresholds are draws, not midpoints — ops/trees.py).
    return model == "Random Forest"


KNOBSPACE = (
    Knob("F16_HIST_NODE_BATCH_CPU", ("4", "6", "8", "12", "16"), "0",
         False, "fit", _cpu_hist,
         "CPU BFS node-batch width of the hist grower; 0 auto-selects "
         "by max_nodes (ops/trees.py _cpu_node_batch)"),
    Knob("F16_HIST_NODE_BATCH", ("64", "128", "256"), "128",
         False, "fit", _device_hist,
         "device (MXU) node-batch width of the hist grower"),
    Knob("F16_HIST_REFINE_TILE", ("0", "128", "256", "512"), "0",
         False, "fit", _refine_families,
         "sample-tile size of the exact-refinement reduce; 0 = one-shot "
         "[N, W] masks (every tile grows the bit-identical forest)"),
    Knob("F16_HIST_BINS", ("32", "48", "64"), "64",
         True, "fit", _hist_families,
         "histogram resolution; candidate selection is bin-granular, so "
         "this MOVES model outputs — winners must re-pass parity"),
    Knob("F16_SHAP_TREE_CHUNK", ("5", "25"), "25",
         False, "shap", _hist_families,
         "trees per SHAP accumulation chunk (ops/treeshap.py, read "
         "per-explain)"),
)


def knobspace(target=None):
    """The registry, optionally filtered by tuning target."""
    if target is None:
        return KNOBSPACE
    return tuple(k for k in KNOBSPACE if k.target == target)


def registered_env_names():
    """Env vars the KnobSpace declares — the set f16lint G108 accepts as
    'this constant is tuner-managed'."""
    return frozenset(k.name for k in KNOBSPACE)


# -- candidate generation ------------------------------------------------


def applicable_knobs(shape, backend, model, *, target="fit", env=None,
                     include_parity=True):
    """Registry entries live for one (shape, backend, model) tuning run.
    A knob the operator already pinned in the environment is excluded —
    an explicit export outranks the search (same precedence the
    plan-time consult enforces, obs/perfdb.tuned_fit_overrides)."""
    env = os.environ if env is None else env
    out = []
    for k in knobspace(target):
        if k.name in env:
            continue
        if not include_parity and k.parity_affecting:
            continue
        if k.applies(tuple(shape), backend, model):
            out.append(k)
    return out


def candidates(knobs):
    """The deterministic rung-0 field: the baseline (today's defaults,
    empty env) plus every single-knob assignment, in registry order.
    Cross-knob composition happens AFTER the halving rungs (the compose
    rung merges each knob's surviving best value), so the field stays
    linear in the domain sizes instead of their product."""
    out = [("base", {})]
    for k in knobs:
        for v in k.domain:
            if str(v) == str(k.default):
                continue  # the baseline already measures the default
            out.append((f"{k.name}={v}", {k.name: str(v)}))
    return out


# -- perfdb seeding ------------------------------------------------------


def family_history_wall(rows, backend, n, n_trees, member_codes):
    """The best committed per-family fit wall (seconds) for this probe
    shape: per source document, the sum of its members' ``config.*``
    fit walls (falling back to total) — the BENCH-history seed that
    sizes probe timeouts and the baseline expectation. None when the
    history carries nothing comparable."""
    sig = f"probe.n{n}.t{n_trees}"
    per_src = {}
    for row in rows or ():
        if row.get("backend") not in (backend, "*"):
            continue
        if row.get("shape") != sig:
            continue
        kernel = str(row.get("kernel") or "")
        if not kernel.startswith("config."):
            continue
        if kernel[len("config."):] not in member_codes:
            continue
        m = row.get("metrics") or {}
        wall = m.get("fit_s", m.get("total_s"))
        if not isinstance(wall, (int, float)):
            continue
        key = (row.get("src"), row.get("round"))
        per_src.setdefault(key, {})[kernel] = float(wall)
    sums = [sum(v.values()) for v in per_src.values()
            if len(v) == len(member_codes)]
    return min(sums) if sums else None


def audit_peak_mb(rows):
    """The largest I401 plan memory envelope on record (audit rows,
    obs/perfdb.rows_from_audit) — the width-veto anchor."""
    peaks = []
    for row in rows or ():
        if not str(row.get("kernel") or "").startswith("audit."):
            continue
        peak = (row.get("metrics") or {}).get("peak_mb")
        if isinstance(peak, (int, float)):
            peaks.append(float(peak))
    return max(peaks) if peaks else None


def mem_vetoed(cand_env, peak_mb, cap_mb):
    """Whether a candidate's node-batch width would scale the audited
    plan envelope past the cap. The grower's per-step working set is
    ~linear in the batch width (the [N, W] one-hots and [F, W, B]
    histograms), so the envelope scales by width/8 (the audited default
    width). No envelope on record means no veto."""
    if peak_mb is None or not cap_mb:
        return False
    width = cand_env.get("F16_HIST_NODE_BATCH_CPU") or \
        cand_env.get("F16_HIST_NODE_BATCH")
    try:
        width = int(width)
    except (TypeError, ValueError):
        return False
    if width <= 8:
        return False
    return peak_mb * (width / 8.0) > cap_mb


# -- the search ----------------------------------------------------------

TuneResult = namedtuple(
    "TuneResult", ["family", "shape", "winner", "winner_env", "wall_s",
                   "base_wall_s", "gain_pct", "walls", "rejected",
                   "recorded"])


def successive_halving(cands, measure, *, reps_schedule=(1, 2, 4),
                       keep=0.5, min_survivors=3, log=None):
    """Deterministic successive halving: every rung measures the
    surviving field at the rung's rep count (walls keep the running min
    across rungs — repetitions only ever sharpen), then keeps the best
    ``keep`` fraction, ties broken by candidate NAME so the same wall
    table always yields the same survivors (the determinism contract
    ``tune --resume`` and the tests pin). Returns {name: wall}."""
    alive = list(cands)
    walls = {}
    for rung, reps in enumerate(reps_schedule):
        for name, env in alive:
            w = measure(env, reps)
            walls[name] = min(walls.get(name, float("inf")), w)
        if log:
            log(f"  rung {rung} (reps={reps}): " + ", ".join(
                f"{n}={walls[n]:.2f}s" for n, _ in alive))
        if len(alive) <= min_survivors or rung == len(reps_schedule) - 1:
            break
        alive.sort(key=lambda c: (walls[c[0]], c[0]))
        alive = alive[:max(min_survivors, int(len(alive) * keep))]
    return walls


def compose_best(knobs, walls, base_wall):
    """The compose rung's candidate: each knob's best measured value
    among those that beat the baseline. Empty when no knob did."""
    env = {}
    for k in knobs:
        best_v, best_w = None, base_wall
        for v in k.domain:
            name = f"{k.name}={v}"
            w = walls.get(name)
            if w is not None and w < best_w:
                best_v, best_w = str(v), w
        if best_v is not None:
            env[k.name] = best_v
    return env


def tune_family(fs_name, model_name, *, backend, n, n_trees, n_folds,
                measure, rows=None, member_codes=(), include_parity=True,
                parity_check=None, min_gain_pct=2.0, cap_mb=3072.0,
                db=None, record=True, log=None):
    """Search one family's knob space and (optionally) record the winner
    as a tuned perfdb row. ``measure(env, reps) -> wall_s`` is the
    oracle (subprocess bench probe in production, injected in tests);
    ``parity_check(env) -> bool`` guards parity-affecting winners —
    None with parity knobs in play means they are skipped up front
    (never accept what cannot be checked)."""
    log = log or (lambda *_: None)
    shape = planner.plan_shape(
        fs_name, model_name, n=n, n_folds=n_folds,
        tree_overrides={m: n_trees for m in ENSEMBLES})
    include_parity = include_parity and parity_check is not None
    knobs = applicable_knobs(shape, backend, model_name,
                             include_parity=include_parity)
    hist_wall = family_history_wall(rows, backend, n, n_trees,
                                    set(member_codes))
    peak_mb = audit_peak_mb(rows)
    field = [(name, env) for name, env in candidates(knobs)
             if not mem_vetoed(env, peak_mb, cap_mb)]
    vetoed = len(candidates(knobs)) - len(field)
    log(f"{fs_name}/{model_name}: {len(field)} candidate(s) over "
        f"{len(knobs)} knob(s)"
        + (f", {vetoed} width(s) vetoed by the {peak_mb:.0f} MB audit "
           f"envelope" if vetoed else "")
        + (f", history seed {hist_wall:.1f}s" if hist_wall else ""))

    walls = successive_halving(field, measure, log=log)
    base_wall = walls.get("base", float("inf"))

    composed = compose_best(knobs, walls, base_wall)
    if composed and len(composed) > 1:
        name = "+".join(f"{k}={v}" for k, v in sorted(composed.items()))
        walls[name] = measure(composed, 4)
        field.append((name, composed))
        log(f"  compose: {name}={walls[name]:.2f}s")

    by_env = dict(field)
    rejected = []

    def pick(pool):
        ranked = sorted(pool, key=lambda name: (walls[name], name))
        return ranked[0] if ranked else "base"

    winner = pick(list(walls))
    while winner != "base":
        env = by_env.get(winner, {})
        parity_knobs = [k for k in knobs if k.parity_affecting
                        and k.name in env]
        if not parity_knobs:
            break
        log(f"  parity re-check for {winner} "
            f"({', '.join(k.name for k in parity_knobs)})")
        if parity_check is not None and parity_check(env):
            break
        rejected.append({"candidate": winner, "reason": "parity",
                         "wall_s": walls[winner]})
        log(f"  REJECTED {winner}: parity harness red")
        walls.pop(winner)
        winner = pick(list(walls))

    wall = walls.get(winner, float("inf"))
    gain = (100.0 * (base_wall - wall) / base_wall
            if base_wall not in (0.0, float("inf")) else 0.0)
    winner_env = dict(by_env.get(winner, {}))
    if winner == "base" or gain < min_gain_pct or not winner_env:
        log(f"  no winner past the {min_gain_pct:.1f}% gain floor "
            f"(best {winner}: {gain:+.1f}%) — defaults stand, no row")
        return TuneResult((fs_name, model_name), shape, "base", {},
                          base_wall, base_wall, 0.0, walls, rejected,
                          None)

    recorded = None
    if record:
        metrics = {"fit_s": round(wall, 4),
                   "base_fit_s": round(base_wall, 4),
                   "gain_pct": round(gain, 2)}
        recorded = perfdb.record_tuned(
            backend, perfdb.shape_sig(shape),
            perfdb.model_kernel(model_name), winner_env, metrics,
            path=db)
    log(f"  WINNER {winner}: {wall:.2f}s vs base {base_wall:.2f}s "
        f"({gain:+.1f}%)" + (" — recorded" if recorded else ""))
    return TuneResult((fs_name, model_name), shape, winner, winner_env,
                      wall, base_wall, gain, walls, rejected, recorded)


# -- production oracles --------------------------------------------------


def _probe_env(backend, cand_env, extra=None):
    env = dict(os.environ)
    env.update(cand_env)
    env.update(extra or {})
    # Probes measure the CANDIDATE env, never the database: a tuned row
    # sneaking into a probe would make the search self-referential.
    env["F16_PERFDB"] = "0"
    if backend == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("PALLAS_AXON_POOL_IPS", "")
    return env


def subprocess_measure(fs_name, model_name, *, backend, n, n_trees,
                       timeout_s, py=None, log=None):
    """The production oracle: each candidate probes in a FRESH
    subprocess (``tune --probe``) because the hist knobs are read at
    import (ops/trees.py) — an in-process sweep would measure the first
    import's values forever. Failure/timeout returns inf (the candidate
    simply loses)."""
    py = py or sys.executable

    def measure(cand_env, reps):
        cmd = [py, "-m", "flake16_framework_tpu", "tune", "--probe",
               "--family", f"{fs_name}/{model_name}",
               "--n", str(n), "--trees", str(n_trees),
               "--reps", str(max(1, int(reps)))]
        try:
            proc = subprocess.run(
                cmd, cwd=REPO, env=_probe_env(backend, cand_env),
                capture_output=True, text=True, timeout=timeout_s)
        except subprocess.TimeoutExpired:
            if log:
                log(f"  probe timeout ({timeout_s:.0f}s): {cand_env}")
            return float("inf")
        for line in reversed((proc.stdout or "").strip().splitlines()):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "wall_s" in rec:
                return float(rec["wall_s"])
        if log:
            tail = (proc.stderr or "").strip().splitlines()[-3:]
            log(f"  probe failed rc={proc.returncode}: {cand_env} "
                f"{' | '.join(tail)}")
        return float("inf")

    return measure


def parity_subprocess_check(backend, *, timeout_s=3600, py=None,
                            log=None):
    """Parity oracle for parity-affecting winners: the repo's parity
    harness (parity.py small tier — the CPU-budget regression guard,
    same machinery as the full assertion tier) under the candidate env.
    Exit 0 is green. Timeout/abnormal exit is red: never accept what
    did not provably pass."""
    py = py or sys.executable

    def check(cand_env):
        t0 = time.time()
        try:
            proc = subprocess.run(
                [py, os.path.join(REPO, "parity.py")], cwd=REPO,
                env=_probe_env(backend, cand_env),
                capture_output=True, text=True, timeout=timeout_s)
        except subprocess.TimeoutExpired:
            if log:
                log(f"  parity TIMEOUT ({timeout_s:.0f}s) under "
                    f"{cand_env}")
            return False
        if log:
            log(f"  parity {'green' if proc.returncode == 0 else 'RED'} "
                f"in {time.time() - t0:.0f}s under {cand_env}")
        return proc.returncode == 0

    return check


# -- CLI -----------------------------------------------------------------


def _bench():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench
    return bench


def run_probe(fs_name, model_name, n, n_trees, reps, out=None):
    """``tune --probe`` body (runs inside the candidate's env): warm the
    family's bench configs through the real engine (bench.py machinery —
    the same plans the headline measures), then report the min
    steady-state wall over ``reps`` whole-family runs."""
    out = out or sys.stdout
    bench = _bench()
    import jax

    bench.configure_jax_cache()
    fam = [k for k in bench.CONFIGS if (k[1], k[4]) ==
           (fs_name, model_name)]
    if not fam:
        raise ValueError(f"no bench configs for family "
                         f"{fs_name}/{model_name}")
    feats, labels, projects, names, pids = bench.make_data(n)
    engine, _ = bench.make_bench_engine(feats, labels, projects, names,
                                        pids, n_trees)
    engine.run_grid(fam)  # compile warm-up
    walls = []
    for _ in range(max(1, reps)):
        t0 = time.time()
        engine.run_grid(fam)
        walls.append(round(time.time() - t0, 4))
    out.write(json.dumps({
        "probe": f"{fs_name}/{model_name}", "n": n, "trees": n_trees,
        "wall_s": min(walls), "walls": walls,
        "backend": jax.default_backend(),
        "knobs": perfdb.knob_snapshot(),
    }) + "\n")
    out.flush()
    return 0


def _bench_families():
    bench = _bench()
    fams, codes = [], {}
    for keys in bench.CONFIGS:
        fam = (keys[1], keys[4])
        if fam[1] in ENSEMBLES and fam not in fams:
            fams.append(fam)
        codes.setdefault(fam, []).append("/".join(keys))
    return fams, codes


def tune_main(argv, out=None):
    """CLI entry for the ``tune`` verb (__main__.py). Returns an exit
    code (0 even when every family keeps its defaults — 'nothing beat
    the baseline' is a valid tuning outcome, not a failure)."""
    import argparse

    out = out or sys.stdout
    ap = argparse.ArgumentParser(
        prog="python -m flake16_framework_tpu tune", add_help=True)
    ap.add_argument("--probe", action="store_true",
                    help="internal: measure ONE candidate in-process")
    ap.add_argument("--family", help="Feature set/Model (probe or "
                    "restrict tuning to one family)")
    ap.add_argument("--n", type=int, default=None,
                    help="probe rows (default: bench CPU fallback shape)")
    ap.add_argument("--trees", type=int, default=None)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--backend", default=None)
    ap.add_argument("--db", default=None, help="perfdb path override")
    ap.add_argument("--min-gain", type=float, default=2.0,
                    help="%% fit-wall gain a winner must clear")
    ap.add_argument("--timeout", type=float, default=None,
                    help="per-probe subprocess timeout (s)")
    ap.add_argument("--parity-timeout", type=float, default=3600.0)
    ap.add_argument("--no-parity-knobs", action="store_true",
                    help="search results-neutral knobs only")
    ap.add_argument("--mem-cap-mb", type=float, default=3072.0)
    ap.add_argument("--dry-run", action="store_true",
                    help="print the candidate field, run nothing")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    bench = _bench()
    n = args.n if args.n is not None else bench.FB_N_TESTS
    n_trees = args.trees if args.trees is not None else bench.FB_N_TREES

    if args.probe:
        if not args.family or "/" not in args.family:
            raise ValueError("--probe needs --family 'FeatureSet/Model'")
        fs_name, model_name = args.family.split("/", 1)
        return run_probe(fs_name, model_name, n, n_trees, args.reps,
                         out=out)

    backend = args.backend or perfdb._current_backend()
    fams, codes = _bench_families()
    if args.family:
        fs_name, model_name = args.family.split("/", 1)
        fams = [f for f in fams if f == (fs_name, model_name)]
        if not fams:
            raise ValueError(f"unknown tuning family {args.family!r}")

    def log(msg):
        print(msg, file=sys.stderr, flush=True)

    # Seed context: the perfdb (backfilled BENCH history + audit
    # envelope rows). Absent/unreadable databases seed nothing.
    db_path = perfdb.default_db(args.db)
    rows = []
    if db_path and os.path.isfile(db_path):
        try:
            rows = perfdb.load(db_path)
        except Exception:
            rows = []

    summary = {"verb": "tune", "backend": backend, "n": n,
               "trees": n_trees, "db": db_path, "families": {},
               "env": {}}
    n_folds = 10  # the study protocol's StratifiedKFold(10)

    if args.dry_run:
        for fam in fams:
            shape = planner.plan_shape(
                *fam, n=n, n_folds=n_folds,
                tree_overrides={m: n_trees for m in ENSEMBLES})
            knobs = applicable_knobs(
                shape, backend, fam[1],
                include_parity=not args.no_parity_knobs)
            summary["families"]["/".join(fam)] = {
                "shape": perfdb.shape_sig(shape),
                "candidates": [name for name, _ in candidates(knobs)],
            }
        out.write(json.dumps(summary) + "\n")
        return 0

    parity_check = None if args.no_parity_knobs else \
        parity_subprocess_check(backend,
                                timeout_s=args.parity_timeout, log=log)

    for fam in fams:
        fs_name, model_name = fam
        hist = family_history_wall(rows, backend, n, n_trees,
                                   set(codes.get(fam, ())))
        timeout_s = args.timeout or max(300.0, 6.0 * (hist or 120.0))
        measure = subprocess_measure(
            fs_name, model_name, backend=backend, n=n, n_trees=n_trees,
            timeout_s=timeout_s, log=log)
        res = tune_family(
            fs_name, model_name, backend=backend, n=n, n_trees=n_trees,
            n_folds=n_folds, measure=measure, rows=rows,
            member_codes=codes.get(fam, ()),
            include_parity=not args.no_parity_knobs,
            parity_check=parity_check, min_gain_pct=args.min_gain,
            cap_mb=args.mem_cap_mb, db=args.db, log=log)
        summary["families"]["/".join(fam)] = {
            "winner": res.winner, "env": res.winner_env,
            "wall_s": None if res.wall_s == float("inf")
            else round(res.wall_s, 3),
            "base_wall_s": None if res.base_wall_s == float("inf")
            else round(res.base_wall_s, 3),
            "gain_pct": round(res.gain_pct, 2),
            "rejected": res.rejected,
            "recorded_crc": (res.recorded or {}).get("crc"),
        }
        summary["env"].update(res.winner_env)

    out.write(json.dumps(summary) + "\n")
    return 0
