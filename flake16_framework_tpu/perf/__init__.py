"""Performance tuning plane (ISSUE 20): the f16tune autotuner.

``perf.tuner`` owns the declared knob space (KNOBSPACE — the typed
registry f16lint's G108 audits kernel constants against) and the
bench-in-the-loop search that turns it into ``tuned`` perfdb rows the
planner consults at plan time (obs/perfdb.tuned_fit_overrides). This
package is import-light on purpose: no jax at import, so the lint/G108
census and the CLI help path never touch a device."""

from flake16_framework_tpu.perf.tuner import (  # noqa: F401
    KNOBSPACE,
    Knob,
    knobspace,
    registered_env_names,
    tune_main,
)
