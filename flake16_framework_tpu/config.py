"""The 2x2x3x6x3 experiment grid as *data*.

The reference holds a grid of instantiated sklearn/imblearn estimator objects
(/root/reference/experiment.py:73-100) and forks processes around them. Here every
axis is an integer code or a static spec, so a single jit-compiled graph can cover
many configs (preprocessing and balancing are runtime codes dispatched with
``lax.switch``; the model and feature-set axes are compile-time static).

Key ordering and naming exactly match the reference grid so ``scores.pkl`` keys are
interchangeable (reference experiment.py:493-498).
"""

import itertools
from dataclasses import dataclass

from flake16_framework_tpu.constants import (
    FLAKY, OD_FLAKY, N_FEATURES, FLAKEFLAGGER_COLS
)

# Axis 0: flaky type -> positive label (reference experiment.py:74-77).
FLAKY_TYPES = {"NOD": FLAKY, "OD": OD_FLAKY}

# Axis 1: feature set -> column indices (reference experiment.py:78-81).
FEATURE_SETS = {
    "Flake16": tuple(range(N_FEATURES)),
    "FlakeFlagger": FLAKEFLAGGER_COLS,
}

# Axis 2: preprocessing codes (reference experiment.py:82-86). All three are
# expressible as one affine transform x' = (x - mu) @ W computed in-graph, so the
# code is a runtime integer, not a compile-time branch.
PREP_NONE, PREP_SCALING, PREP_PCA = 0, 1, 2
PREPROCESSINGS = {"None": PREP_NONE, "Scaling": PREP_SCALING, "PCA": PREP_PCA}

# Axis 3: balancing codes (reference experiment.py:87-94). Dispatched via
# ``lax.switch`` over kernels sharing one pairwise-distance primitive.
BAL_NONE, BAL_TOMEK, BAL_SMOTE, BAL_ENN, BAL_SMOTE_ENN, BAL_SMOTE_TOMEK = range(6)
BALANCINGS = {
    "None": BAL_NONE,
    "Tomek Links": BAL_TOMEK,
    "SMOTE": BAL_SMOTE,
    "ENN": BAL_ENN,
    "SMOTE ENN": BAL_SMOTE_ENN,
    "SMOTE Tomek": BAL_SMOTE_TOMEK,
}


@dataclass(frozen=True)
class ModelSpec:
    """Static description of a tree-ensemble model (compile-time constant).

    Captures the sklearn 1.0.2 defaults of the three reference models
    (reference experiment.py:96-98; SURVEY.md §2 table B): 100-tree ensembles,
    gini, unbounded depth, min_samples_split=2, min_samples_leaf=1; RF/ET use
    max_features=sqrt(n_features), DT uses all features.
    """

    name: str
    n_trees: int
    bootstrap: bool
    random_splits: bool  # True: ExtraTrees uniform-random thresholds
    sqrt_features: bool  # True: sqrt(F) candidate features per split


MODELS = {
    "Extra Trees": ModelSpec("Extra Trees", 100, False, True, True),
    "Random Forest": ModelSpec("Random Forest", 100, True, False, True),
    "Decision Tree": ModelSpec("Decision Tree", 1, False, False, False),
}

GRID_AXES = (FLAKY_TYPES, FEATURE_SETS, PREPROCESSINGS, BALANCINGS, MODELS)


def iter_config_keys():
    """All 216 config key-tuples in the reference sweep order
    (reference experiment.py:494: itertools.product over grid dict keys)."""
    return itertools.product(*[tuple(d.keys()) for d in GRID_AXES])


def resolve_config(config_keys):
    """Key tuple -> (flaky_label, feature_cols, prep_code, bal_code, ModelSpec)."""
    flaky_type, feature_set, prep, bal, model = config_keys
    return (
        FLAKY_TYPES[flaky_type],
        FEATURE_SETS[feature_set],
        PREPROCESSINGS[prep],
        BALANCINGS[bal],
        MODELS[model],
    )


# The two configs explained with Tree SHAP (reference experiment.py:523-526).
SHAP_CONFIGS = (
    ("NOD", "Flake16", "Scaling", "SMOTE Tomek", "Extra Trees"),
    ("OD", "Flake16", "Scaling", "SMOTE", "Random Forest"),
)
