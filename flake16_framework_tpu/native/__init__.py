"""On-demand build + load of the native collation fast path.

``load()`` compiles ``collate_fast.cc`` into ``_collate_fast.so`` next to the
source on first use (g++, CPython C API — no pybind11 in this image) and
imports it; it returns None when no toolchain is available or the build
fails, in which case runner/collate.py keeps its pure-Python implementations.
The build is atomic (unique temp + rename) so concurrent processes race
safely, and the .so is rebuilt whenever the source is newer.
"""

import importlib.util
import os
import subprocess
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "collate_fast.cc")
_SO = os.path.join(_DIR, "_collate_fast.so")

_cached = False
_module = None


def _build():
    include = sysconfig.get_paths()["include"]
    tmp = f"{_SO}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", f"-I{include}", _SRC,
             "-o", tmp],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _SO)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def load():
    """The native module, or None (cached after the first attempt)."""
    global _cached, _module
    if _cached:
        return _module
    _cached = True
    try:
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            _build()
        spec = importlib.util.spec_from_file_location(
            "flake16_framework_tpu.native._collate_fast", _SO
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        _module = module
    except Exception:
        _module = None
    return _module
