"""On-demand build + load of the native (C++) fast paths.

``load(name)`` compiles ``<name>.cc`` into ``_<name>.so`` next to the
source on first use (g++, CPython C API — no pybind11 in this image) and
imports it; it returns None when no toolchain is available or the build
fails, in which case callers keep their pure-Python implementations.
The build is atomic (unique temp + rename) so concurrent processes race
safely, and the .so is rebuilt whenever the source is newer.

Modules:
- ``collate_fast`` — L3 collation hot loops (runner/collate.py)
- ``treeshap_cext`` — shap-0.40-equivalent C Tree SHAP, the bench's
  single-host baseline (bench.py)
"""

import importlib.util
import os
import subprocess
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))

_cache = {}


def _build(src, so):
    include = sysconfig.get_paths()["include"]
    tmp = f"{so}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", f"-I{include}", src,
             "-o", tmp],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, so)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def load(name="collate_fast"):
    """The named native module, or None (cached after the first attempt)."""
    if name in _cache:
        return _cache[name]
    _cache[name] = None
    src = os.path.join(_DIR, f"{name}.cc")
    so = os.path.join(_DIR, f"_{name}.so")
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            _build(src, so)
        spec = importlib.util.spec_from_file_location(
            f"flake16_framework_tpu.native._{name}", so
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        _cache[name] = module
    except Exception:
        _cache[name] = None
    return _cache[name]
