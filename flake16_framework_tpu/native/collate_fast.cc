// Native fast path for the L3 collation hot loops (SURVEY.md §3.2: the
// tests-verb hot loop is per-test set x churn-dict crunching; the reference
// leans on coverage.py's C numbits codec for the same stage,
// experiment.py:295-299, 362-373).
//
// Drop-in CPython replacements with identical contracts to the pure-Python
// implementations in runner/collate.py:
//   numbits_to_lines(bytes) -> set[int]
//   coverage_features(cov: {file: set[int]}, test_files, churn) -> (n, n, n)
//
// Built on demand by native/__init__.py with g++; runner/collate.py
// dispatches numbits_to_lines / coverage_features here and falls back to
// its Python implementations when the toolchain or build is unavailable.
// tests/test_native_collate.py asserts native/python parity and the
// micro-bench win.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

static PyObject *numbits_to_lines(PyObject *, PyObject *arg) {
  Py_buffer buf;
  if (PyObject_GetBuffer(arg, &buf, PyBUF_SIMPLE) < 0) return nullptr;

  PyObject *out = PySet_New(nullptr);
  if (!out) {
    PyBuffer_Release(&buf);
    return nullptr;
  }

  const unsigned char *bytes = static_cast<const unsigned char *>(buf.buf);
  for (Py_ssize_t n = 0; n < buf.len; ++n) {
    unsigned int byte = bytes[n];
    while (byte) {
      int k = __builtin_ctz(byte);
      byte &= byte - 1;
      PyObject *v = PyLong_FromSsize_t(8 * n + k);
      if (!v || PySet_Add(out, v) < 0) {
        Py_XDECREF(v);
        Py_DECREF(out);
        PyBuffer_Release(&buf);
        return nullptr;
      }
      Py_DECREF(v);
    }
  }
  PyBuffer_Release(&buf);
  return out;
}

static PyObject *coverage_features(PyObject *, PyObject *args) {
  PyObject *cov, *test_files, *churn;
  if (!PyArg_ParseTuple(args, "OOO", &cov, &test_files, &churn))
    return nullptr;

  long long n_lines = 0, n_changes = 0, n_src_lines = 0;

  PyObject *file, *lines;
  Py_ssize_t pos = 0;
  while (PyDict_Next(cov, &pos, &file, &lines)) {
    Py_ssize_t size = PyObject_Size(lines);
    if (size < 0) return nullptr;
    n_lines += size;

    int is_test = PySequence_Contains(test_files, file);
    if (is_test < 0) return nullptr;
    if (!is_test) n_src_lines += size;

    PyObject *file_churn = PyDict_GetItemWithError(churn, file);  // borrowed
    if (!file_churn) {
      if (PyErr_Occurred()) return nullptr;
      continue;  // churn.get(file, {}) semantics
    }

    PyObject *iter = PyObject_GetIter(lines);
    if (!iter) return nullptr;
    PyObject *line;
    while ((line = PyIter_Next(iter))) {
      PyObject *count = PyDict_GetItemWithError(file_churn, line);  // borrowed
      Py_DECREF(line);
      if (count) {
        long long c = PyLong_AsLongLong(count);
        if (c == -1 && PyErr_Occurred()) {
          Py_DECREF(iter);
          return nullptr;
        }
        n_changes += c;
      } else if (PyErr_Occurred()) {
        Py_DECREF(iter);
        return nullptr;
      }
    }
    Py_DECREF(iter);
    if (PyErr_Occurred()) return nullptr;
  }

  return Py_BuildValue("(LLL)", n_lines, n_changes, n_src_lines);
}

static PyMethodDef methods[] = {
    {"numbits_to_lines", numbits_to_lines, METH_O,
     "Decode a coverage numbits blob into a set of line numbers."},
    {"coverage_features", coverage_features, METH_VARARGS,
     "(covered lines, churn-weighted covered changes, source-only covered "
     "lines) for one test's coverage dict."},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_collate_fast",
    "Native collation hot loops (see module header).", -1, methods,
};

PyMODINIT_FUNC PyInit__collate_fast(void) {
  return PyModule_Create(&moduledef);
}
