"""Python face of the native Tree SHAP baseline (treeshap_cext.cc).

``forest_shap_class0_cext`` mirrors the numpy oracle's contract
(tests/ref_treeshap.py ``forest_shap_class0_ref``: a list of per-tree
``(children_left, children_right, feature, threshold, value01)`` tuples,
as produced by ``sklearn_forest_trees``) so the bench can swap baselines
1:1. Returns None when the native toolchain is unavailable."""

import numpy as np

from flake16_framework_tpu import native


def forest_shap_class0_cext(forest_trees, x):
    """Mean class-0 SHAP [S, F] over the forest via the C extension, or
    None when it can't be built. Trees are padded to a common node count
    with self-contained leaves (feature -1, zero cover) the recursion
    never visits."""
    mod = native.load("treeshap_cext")
    if mod is None:
        return None
    t = len(forest_trees)
    m = max(tree[0].shape[0] for tree in forest_trees)
    left = np.full((t, m), -1, np.int32)
    right = np.full((t, m), -1, np.int32)
    feature = np.full((t, m), -1, np.int32)
    threshold = np.zeros((t, m), np.float64)
    value01 = np.zeros((t, m, 2), np.float64)
    for i, (le, ri, fe, th, v) in enumerate(forest_trees):
        k = le.shape[0]
        left[i, :k] = le
        right[i, :k] = ri
        feature[i, :k] = fe
        threshold[i, :k] = th
        value01[i, :k] = v
    x = np.ascontiguousarray(x, np.float64)
    s, f = x.shape
    phi = np.zeros((s, f), np.float64)
    mod.forest_shap_class0(  # ndarrays pass as buffers, no copies
        left, right, feature, threshold, value01, x, phi, t, m, s, f,
    )
    return phi
