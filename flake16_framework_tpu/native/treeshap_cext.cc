// Native single-host Tree SHAP baseline — the compiled equivalent of
// shap.TreeExplainer's C extension (shap 0.40, feature_perturbation=
// "tree_path_dependent"; shap is not installed in this environment, so the
// bench re-derives the C-extension-grade baseline itself rather than
// benching against a numpy stand-in that would inflate the reported win).
//
// Implements the classic per-sample recursive EXTEND/UNWIND algorithm
// (Lundberg et al., "Consistent Individualized Feature Attribution for Tree
// Ensembles", Algorithm 2) exactly as the reference stack executes it:
// scalar recursion per (sample, tree), O(L * D^2) per pair. Semantics match
// tests/ref_treeshap.py (the vectorized numpy oracle) and are pinned
// against it by tests/test_native_treeshap.py.
//
//   forest_shap_class0(left, right, feature, threshold, value01, x, phi,
//                      T, M, S, F) -> None
//     left/right/feature: int32 [T, M] child ids / split features (<0 leaf)
//     threshold:          float64 [T, M]
//     value01:            float64 [T, M, 2] cover-weighted class counts
//     x:                  float64 [S, F]
//     phi (out, writable) float64 [S, F] — MEAN class-0 SHAP over the T
//                         trees (leaf value = value01[m,0] / cover[m])
//
// Built on demand by native/__init__.py (g++, CPython C API); bench.py
// falls back to the numpy oracle when the toolchain is unavailable and
// says so in its detail line.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct PathElement {
  int feature_index;
  double zero_fraction;
  double one_fraction;
  double pweight;
};

void extend_path(PathElement *unique_path, int unique_depth,
                 double zero_fraction, double one_fraction,
                 int feature_index) {
  unique_path[unique_depth].feature_index = feature_index;
  unique_path[unique_depth].zero_fraction = zero_fraction;
  unique_path[unique_depth].one_fraction = one_fraction;
  unique_path[unique_depth].pweight = unique_depth == 0 ? 1.0 : 0.0;
  for (int i = unique_depth - 1; i >= 0; --i) {
    unique_path[i + 1].pweight += one_fraction * unique_path[i].pweight *
                                  (i + 1.0) / (unique_depth + 1.0);
    unique_path[i].pweight = zero_fraction * unique_path[i].pweight *
                             (unique_depth - i) / (unique_depth + 1.0);
  }
}

void unwind_path(PathElement *unique_path, int unique_depth, int path_index) {
  const double one_fraction = unique_path[path_index].one_fraction;
  const double zero_fraction = unique_path[path_index].zero_fraction;
  double next_one_portion = unique_path[unique_depth].pweight;
  for (int i = unique_depth - 1; i >= 0; --i) {
    if (one_fraction != 0) {
      const double tmp = unique_path[i].pweight;
      unique_path[i].pweight = next_one_portion * (unique_depth + 1.0) /
                               ((i + 1.0) * one_fraction);
      next_one_portion = tmp - unique_path[i].pweight * zero_fraction *
                                   (unique_depth - i) / (unique_depth + 1.0);
    } else {
      unique_path[i].pweight = (unique_path[i].pweight * (unique_depth + 1.0)) /
                               (zero_fraction * (unique_depth - i));
    }
  }
  for (int i = path_index; i < unique_depth; ++i) {
    unique_path[i].feature_index = unique_path[i + 1].feature_index;
    unique_path[i].zero_fraction = unique_path[i + 1].zero_fraction;
    unique_path[i].one_fraction = unique_path[i + 1].one_fraction;
  }
}

double unwound_path_sum(const PathElement *unique_path, int unique_depth,
                        int path_index) {
  const double one_fraction = unique_path[path_index].one_fraction;
  const double zero_fraction = unique_path[path_index].zero_fraction;
  double next_one_portion = unique_path[unique_depth].pweight;
  double total = 0;
  for (int i = unique_depth - 1; i >= 0; --i) {
    if (one_fraction != 0) {
      const double tmp = next_one_portion * (unique_depth + 1.0) /
                         ((i + 1.0) * one_fraction);
      total += tmp;
      next_one_portion = unique_path[i].pweight -
                         tmp * zero_fraction * (unique_depth - i) /
                             (unique_depth + 1.0);
    } else {
      total += (unique_path[i].pweight / zero_fraction) /
               ((unique_depth - i) / (unique_depth + 1.0));
    }
  }
  return total;
}

struct Tree {
  const int32_t *left;
  const int32_t *right;
  const int32_t *feature;
  const double *threshold;
  const double *value01;  // [M, 2]
  std::vector<double> cover;
  std::vector<double> leaf_p0;
};

void tree_shap_recurse(const Tree &t, const double *xrow, double *phi_row,
                       int node, PathElement *parent_path, int unique_depth,
                       double parent_zero_fraction,
                       double parent_one_fraction, int parent_feature_index) {
  // each call works on its own copy of the path (the recursion's two
  // branches mutate it), laid out contiguously after the parent's segment
  PathElement *unique_path = parent_path + unique_depth + 1;
  std::memcpy(unique_path, parent_path,
              (unique_depth + 1) * sizeof(PathElement));
  extend_path(unique_path, unique_depth, parent_zero_fraction,
              parent_one_fraction, parent_feature_index);

  const int f = t.feature[node];
  if (f < 0) {  // leaf
    for (int i = 1; i <= unique_depth; ++i) {
      const double w = unwound_path_sum(unique_path, unique_depth, i);
      const PathElement &el = unique_path[i];
      phi_row[el.feature_index] +=
          w * (el.one_fraction - el.zero_fraction) * t.leaf_p0[node];
    }
    return;
  }

  const int hot = xrow[f] <= t.threshold[node] ? t.left[node] : t.right[node];
  const int cold = hot == t.left[node] ? t.right[node] : t.left[node];
  const double denom = t.cover[node] > 0 ? t.cover[node] : 1e-30;
  const double hot_zero_fraction = t.cover[hot] / denom;
  const double cold_zero_fraction = t.cover[cold] / denom;
  double incoming_zero_fraction = 1.0;
  double incoming_one_fraction = 1.0;

  // a feature already on the path is unwound and folded into the new element
  int path_index = 1;
  for (; path_index <= unique_depth; ++path_index)
    if (unique_path[path_index].feature_index == f) break;
  if (path_index != unique_depth + 1) {
    incoming_zero_fraction = unique_path[path_index].zero_fraction;
    incoming_one_fraction = unique_path[path_index].one_fraction;
    unwind_path(unique_path, unique_depth, path_index);
    unique_depth -= 1;
  }

  tree_shap_recurse(t, xrow, phi_row, hot, unique_path, unique_depth + 1,
                    hot_zero_fraction * incoming_zero_fraction,
                    incoming_one_fraction, f);
  tree_shap_recurse(t, xrow, phi_row, cold, unique_path, unique_depth + 1,
                    cold_zero_fraction * incoming_zero_fraction, 0.0, f);
}

int tree_max_depth(const Tree &t, int m) {
  std::vector<int> depth(m, -1);
  depth[0] = 0;
  int best = 0;
  for (int i = 0; i < m; ++i) {  // BFS ids are parent-before-child
    if (depth[i] < 0) continue;
    best = depth[i] > best ? depth[i] : best;
    const int l = t.left[i], r = t.right[i];
    if (l >= 0 && l < m) depth[l] = depth[i] + 1;
    if (r >= 0 && r < m) depth[r] = depth[i] + 1;
  }
  return best;
}

PyObject *forest_shap_class0(PyObject *, PyObject *args) {
  Py_buffer left, right, feature, threshold, value01, x, phi;
  int T, M, S, F;
  if (!PyArg_ParseTuple(args, "y*y*y*y*y*y*w*iiii", &left, &right, &feature,
                        &threshold, &value01, &x, &phi, &T, &M, &S, &F))
    return nullptr;

  struct Releaser {
    std::vector<Py_buffer *> bufs;
    ~Releaser() {
      for (auto *b : bufs) PyBuffer_Release(b);
    }
  } rel;
  rel.bufs = {&left, &right, &feature, &threshold, &value01, &x, &phi};

  if (left.len < (Py_ssize_t)sizeof(int32_t) * T * M ||
      right.len < (Py_ssize_t)sizeof(int32_t) * T * M ||
      feature.len < (Py_ssize_t)sizeof(int32_t) * T * M ||
      threshold.len < (Py_ssize_t)sizeof(double) * T * M ||
      value01.len < (Py_ssize_t)sizeof(double) * T * M * 2 ||
      x.len < (Py_ssize_t)sizeof(double) * S * F ||
      phi.len < (Py_ssize_t)sizeof(double) * S * F) {
    PyErr_SetString(PyExc_ValueError, "buffer too small for claimed shape");
    return nullptr;
  }

  const double *xp = static_cast<const double *>(x.buf);
  double *php = static_cast<double *>(phi.buf);
  std::memset(php, 0, sizeof(double) * S * F);

  Py_BEGIN_ALLOW_THREADS;
  for (int ti = 0; ti < T; ++ti) {
    Tree t;
    t.left = static_cast<const int32_t *>(left.buf) + (size_t)ti * M;
    t.right = static_cast<const int32_t *>(right.buf) + (size_t)ti * M;
    t.feature = static_cast<const int32_t *>(feature.buf) + (size_t)ti * M;
    t.threshold = static_cast<const double *>(threshold.buf) + (size_t)ti * M;
    t.value01 = static_cast<const double *>(value01.buf) + (size_t)ti * M * 2;
    t.cover.resize(M);
    t.leaf_p0.resize(M);
    for (int m = 0; m < M; ++m) {
      t.cover[m] = t.value01[2 * m] + t.value01[2 * m + 1];
      t.leaf_p0[m] = t.value01[2 * m] / (t.cover[m] > 0 ? t.cover[m] : 1e-30);
    }
    const int maxd = tree_max_depth(t, M);
    // recursion chain holds one path copy per level; level d's copy has
    // d + 2 elements (incl. the dummy), total bounded by the arena below
    std::vector<PathElement> arena(((size_t)maxd + 2) * (maxd + 3) / 2 + 2);
    for (int s = 0; s < S; ++s) {
      arena[0] = {-1, 1.0, 1.0, 1.0};
      // depth-0 call copies from arena[0..0] into arena[1..]
      tree_shap_recurse(t, xp + (size_t)s * F, php + (size_t)s * F, 0,
                        arena.data(), 0, 1.0, 1.0, -1);
    }
  }
  const double inv = 1.0 / (T > 0 ? T : 1);
  for (Py_ssize_t i = 0; i < (Py_ssize_t)S * F; ++i) php[i] *= inv;
  Py_END_ALLOW_THREADS;

  Py_RETURN_NONE;
}

PyMethodDef methods[] = {
    {"forest_shap_class0", forest_shap_class0, METH_VARARGS,
     "Mean class-0 path-dependent Tree SHAP over a forest (C baseline)."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_treeshap_cext",
    "shap 0.40-equivalent C Tree SHAP baseline", -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__treeshap_cext(void) {
  return PyModule_Create(&moduledef);
}
