"""Single trees on IDENTICAL bootstrap weights: which arm closes the gap?

Round-3 found single trees at -0.020 vs sklearn on pinned weights (unit
weights match at +0.003), but the bins sweep that 'exonerated threshold
resolution' measured the ENSEMBLE delta. This re-runs the single-tree
observable across growers/bins: if the exact grower reads ~0 while hist
stays low at any bin count, the deviation is hist-structural; if exact is
also low, the mechanism is shared (feature sampling / stopping).
"""
import json, sys
sys.path.insert(0, '/root/repo')
import numpy as np, jax
from sklearn.tree import DecisionTreeClassifier
from sklearn.metrics import f1_score
from flake16_framework_tpu.utils.synth import make_dataset
from flake16_framework_tpu.ops import trees
from flake16_framework_tpu.config import FLAKY_TYPES

feats, labels, pids = make_dataset(n_tests=4000, seed=7, nod_bump=2.5,
                                   od_bump=1.8, noise_sigma=0.35)
y = (labels == FLAKY_TYPES["NOD"]).astype(int)
x = feats.astype(np.float32)
mu, sd = x.mean(0), x.std(0); sd[sd == 0] = 1
x = (x - mu) / sd
rng = np.random.RandomState(0)
idx = rng.permutation(len(y)); tr, te = idx[:3000], idx[3000:]
xtr, ytr = x[tr], y[tr]

SEEDS = 10
ws = [np.bincount(np.random.RandomState(100 + s).randint(0, 3000, 3000),
                  minlength=3000).astype(np.float32) for s in range(SEEDS)]

sk = []
for s in range(SEEDS):
    m = DecisionTreeClassifier(max_features="sqrt", random_state=s
                               ).fit(xtr, ytr, sample_weight=ws[s])
    sk.append(f1_score(y[te], m.predict(x[te])))
print(json.dumps({"arm": "sklearn", "mean": round(float(np.mean(sk)), 4),
                  "sd": round(float(np.std(sk)), 4)}), flush=True)


def run_arm(tag, fit):
    f1s = []
    for s in range(SEEDS):
        f = fit(s)
        p = np.asarray(trees.predict_proba(f, x[te]))
        f1s.append(f1_score(y[te], p[:, 1] > 0.5))
    print(json.dumps({
        "arm": tag, "mean": round(float(np.mean(f1s)), 4),
        "sd": round(float(np.std(f1s)), 4),
        "delta_vs_sk": round(float(np.mean(f1s) - np.mean(sk)), 4)},
    ), flush=True)


for nb in (64, 256, 1024):
    run_arm(f"hist_b{nb}", lambda s, nb=nb: trees.fit_forest_hist(
        xtr, ytr.astype(bool), ws[s], jax.random.PRNGKey(s),
        n_trees=1, bootstrap=False, random_splits=False,
        sqrt_features=True, max_depth=48, max_nodes=4 * 3000, n_bins=nb))

run_arm("exact", lambda s: trees.fit_forest(
    xtr, ytr.astype(bool), ws[s], jax.random.PRNGKey(s),
    n_trees=1, bootstrap=False, random_splits=False,
    sqrt_features=True, max_depth=48, max_nodes=4 * 3000))
