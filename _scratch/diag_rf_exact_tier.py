"""RF parity probe config through the EXACT grower tier (1+ seeds)."""
import json, os, sys, time
sys.path.insert(0, '/root/repo')
import numpy as np
import parity
from flake16_framework_tpu.utils.synth import make_dataset

feats, labels, pids = make_dataset(n_tests=4000, seed=7, nod_bump=2.5,
                                   od_bump=1.8, noise_sigma=0.35)
cache = json.load(open('/root/repo/parity_sklearn_n4000_t100.json'))
keys = ("NOD", "Flake16", "Scaling", "SMOTE", "Random Forest")
sk = np.array(cache['f1s']['/'.join(keys)][:6])
seeds = [int(s) for s in sys.argv[1:]] or [0]
for s in seeds:
    t0 = time.time()
    f1 = parity.ours_config_f1s(feats, labels, pids, keys, n_trees=100,
                                seeds=[s], grower="exact")[0]
    rec = {"arm": "rf_exact_tier", "seed": s, "f1": round(float(f1), 4),
           "sklearn_mean": round(float(sk.mean()), 4),
           "delta_1seed": round(float(f1 - sk.mean()), 4),
           "wall_s": round(time.time() - t0, 1)}
    print(json.dumps(rec), flush=True)
    with open('/root/repo/_scratch/parity_diag.jsonl', 'a') as fd:
        fd.write(json.dumps(rec) + '\n')
