"""Exact-tier RF vs sklearn at mid size (N=2000, t=100) — the at-scale
confidence datum the full-size CPU run cannot afford (~2 h/seed there).

Same config family as the criterion row (Scaling/SMOTE), same harness
machinery; sklearn side computed fresh (no cache exists at this size).
"""
import json, sys, time
sys.path.insert(0, '/root/repo')
import numpy as np
import parity
from flake16_framework_tpu.utils.synth import make_dataset

N, T, K_SK, K_X = 2000, 100, 6, 3
feats, labels, pids = make_dataset(n_tests=N, seed=7, nod_bump=2.5,
                                   od_bump=1.8, noise_sigma=0.35)
keys = ("NOD", "Flake16", "Scaling", "SMOTE", "Random Forest")

t0 = time.time()
sk = np.array([parity.sklearn_config_f1(feats, labels, keys, n_trees=T,
                                        seed=s) for s in range(K_SK)])
print(json.dumps({"arm": "sklearn_mid", "mean": round(float(sk.mean()), 4),
                  "sd": round(float(sk.std()), 4),
                  "wall_s": round(time.time() - t0, 1)}), flush=True)

for s in range(K_X):
    t0 = time.time()
    f1 = parity.ours_config_f1s(feats, labels, pids, keys, n_trees=T,
                                seeds=[s], grower="exact")[0]
    rec = {"arm": "rf_exact_mid", "n_tests": N, "seed": s,
           "f1": round(float(f1), 4),
           "sklearn_mean": round(float(sk.mean()), 4),
           "sklearn_sd": round(float(sk.std()), 4),
           "delta_1seed": round(float(f1 - sk.mean()), 4),
           "wall_s": round(time.time() - t0, 1)}
    print(json.dumps(rec), flush=True)
    with open('/root/repo/_scratch/parity_diag.jsonl', 'a') as fd:
        fd.write(json.dumps(rec) + '\n')
