"""RF PARITY PROBE config (Scaling/SMOTE) at higher bin counts.

The round-4 single-tree ablation (diag_tree_arms.py) showed the identical-
weights single-tree gap is bins-driven: -0.0204 at 64 bins, noise-level at
256+, exact grower -0.0009. The round-3 ensemble bins sweep that read flat
(+0.07) ran on the no-SMOTE DIAGNOSTIC config; the criterion config was
only ever tried at 128. This measures the criterion config itself.
"""
import json, os, sys, time
sys.path.insert(0, '/root/repo')
import numpy as np
import parity
from flake16_framework_tpu.utils.synth import make_dataset

feats, labels, pids = make_dataset(n_tests=4000, seed=7, nod_bump=2.5,
                                   od_bump=1.8, noise_sigma=0.35)
cache = json.load(open('/root/repo/parity_sklearn_n4000_t100.json'))
keys = ("NOD", "Flake16", "Scaling", "SMOTE", "Random Forest")
sk = np.array(cache['f1s']['/'.join(keys)][:6])
seeds = range(int(os.environ.get("DIAG_SEEDS", "6")))
out = {"config": "/".join(keys),
       "bins": os.environ.get("F16_HIST_BINS", "64"),
       "k": len(list(seeds)), "sklearn_mean": round(float(sk.mean()), 4)}
t0 = time.time()
ours = np.array(parity.ours_config_f1s(feats, labels, pids, keys,
                                       n_trees=100, seeds=seeds))
out.update(ours_mean=round(float(ours.mean()), 4),
           ours_sd=round(float(ours.std()), 4),
           delta=round(float(ours.mean() - sk.mean()), 4),
           se=round(float(ours.std() / max(len(ours) - 1, 1) ** 0.5), 4),
           wall_s=round(time.time() - t0, 1))
print(json.dumps(out), flush=True)
with open('/root/repo/_scratch/parity_diag.jsonl', 'a') as fd:
    fd.write(json.dumps(out) + '\n')
