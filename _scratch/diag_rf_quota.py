"""A/B of FEATURE_QUOTA on the cleanest RF signal (Scaling/None, no SMOTE).

Round-3 recorded ours=0.5833 (+0.0703 vs sklearn 0.513) on this config
with the "informative" quota. If sklearn-quota semantics are the
mechanism, this delta should collapse toward 0.
"""
import json, os, sys, time
sys.path.insert(0, '/root/repo')
import numpy as np
import parity
from flake16_framework_tpu.utils.synth import make_dataset
from flake16_framework_tpu.ops import trees

feats, labels, pids = make_dataset(n_tests=4000, seed=7, nod_bump=2.5,
                                   od_bump=1.8, noise_sigma=0.35)
keys = tuple(os.environ.get(
    "DIAG_CONFIG", "NOD/Flake16/Scaling/None/Random Forest").split("/"))
SK = {"NOD/Flake16/Scaling/None/Random Forest": (0.513, 0.0056)}
cache_path = '/root/repo/parity_sklearn_n4000_t100.json'
ck = "/".join(keys)
if os.path.exists(cache_path):
    cache = json.load(open(cache_path))
    if ck in cache.get('f1s', {}):
        arr = np.array(cache['f1s'][ck][:6])
        SK[ck] = (float(arr.mean()), float(arr.std()))
sk_mean, sk_sd = SK[ck]
seeds = range(int(os.environ.get("DIAG_SEEDS", "6")))
t0 = time.time()
ours = np.array(parity.ours_config_f1s(feats, labels, pids, keys,
                                       n_trees=100, seeds=seeds))
out = {"config": ck, "quota": trees.FEATURE_QUOTA,
       "bins": os.environ.get("F16_HIST_BINS", "64"),
       "k": len(ours), "sklearn_mean": round(sk_mean, 4),
       "ours_mean": round(float(ours.mean()), 4),
       "ours_sd": round(float(ours.std()), 4),
       "delta": round(float(ours.mean() - sk_mean), 4),
       "wall_s": round(time.time() - t0, 1)}
print(json.dumps(out), flush=True)
with open('/root/repo/_scratch/parity_diag.jsonl', 'a') as fd:
    fd.write(json.dumps(out) + '\n')
