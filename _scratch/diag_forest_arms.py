"""Pinned-weight forests across growers/bins, with co-variation stats.

Round-4 state: single trees on pinned weights converge to sklearn by 256
bins (diag_tree_arms), yet ensembles stay +0.07 at every bin count and
quota semantics don't move it. This runs the pinned-weight FOREST
experiment (identical per-tree bootstrap weights, 100 trees) per arm and
records the stats that separate the candidate mechanisms:
  - ens_f1 / delta: the headline observable
  - tree_f1: mean individual strength (bins artifact shows here)
  - pos_rate: ensemble predicted-positive rate (threshold-shift mechanism)
  - pair_agree: mean pairwise per-tree hard-prediction agreement
    (decorrelation mechanism shows here)
Arms: hist@64, hist@256, exact, vs sklearn on the same weights.
"""
import functools, json, sys, time
sys.path.insert(0, '/root/repo')
import numpy as np, jax
from sklearn.tree import DecisionTreeClassifier
from sklearn.metrics import f1_score
from flake16_framework_tpu.utils.synth import make_dataset
from flake16_framework_tpu.ops import trees
from flake16_framework_tpu.config import FLAKY_TYPES

feats, labels, pids = make_dataset(n_tests=4000, seed=7, nod_bump=2.5,
                                   od_bump=1.8, noise_sigma=0.35)
y = (labels == FLAKY_TYPES["NOD"]).astype(int)
x = feats.astype(np.float32)
mu, sd = x.mean(0), x.std(0); sd[sd == 0] = 1
x = (x - mu) / sd
rng = np.random.RandomState(0)
idx = rng.permutation(len(y)); tr, te = idx[:3000], idx[3000:]
xtr, ytr = x[tr], y[tr]
T = 100


def stats(tag, seed, preds_soft):
    """preds_soft [T, n_te] = per-tree P(class 1)."""
    hard = preds_soft > 0.5
    ens = preds_soft.mean(0)
    tree_f1 = float(np.mean([f1_score(y[te], h) for h in hard]))
    # pairwise agreement over 30 random tree pairs (cost bound)
    r = np.random.RandomState(0)
    pairs = [(r.randint(T), r.randint(T)) for _ in range(30)]
    agree = float(np.mean([np.mean(hard[a] == hard[b])
                           for a, b in pairs if a != b]))
    rec = {"arm": tag, "seed": seed,
           "ens_f1": round(float(f1_score(y[te], ens > 0.5)), 4),
           "tree_f1": round(tree_f1, 4),
           "pos_rate": round(float((ens > 0.5).mean()), 4),
           "pair_agree": round(agree, 4)}
    print(json.dumps(rec), flush=True)
    return rec


def run_seed(seed):
    r = np.random.RandomState(1000 + seed)
    ws = [np.bincount(r.randint(0, 3000, 3000), minlength=3000)
          .astype(np.float32) for _ in range(T)]

    ps = np.zeros((T, len(te)))
    for t, w in enumerate(ws):
        m = DecisionTreeClassifier(max_features="sqrt",
                                   random_state=seed * 1000 + t
                                   ).fit(xtr, ytr, sample_weight=w)
        ps[t] = m.predict_proba(x[te])[:, 1]
    sk = stats("sklearn", seed, ps)

    arms = {
        "hist_b64": jax.jit(functools.partial(
            trees.fit_forest_hist, n_trees=1, bootstrap=False,
            random_splits=False, sqrt_features=True, max_depth=48,
            max_nodes=4 * 3000, n_bins=64)),
        "hist_b256": jax.jit(functools.partial(
            trees.fit_forest_hist, n_trees=1, bootstrap=False,
            random_splits=False, sqrt_features=True, max_depth=48,
            max_nodes=4 * 3000, n_bins=256)),
        "exact": jax.jit(functools.partial(
            trees.fit_forest, n_trees=1, bootstrap=False,
            random_splits=False, sqrt_features=True, max_depth=48,
            max_nodes=4 * 3000)),
    }
    for tag, fit1 in arms.items():
        t0 = time.time()
        po = np.zeros((T, len(te)))
        for t, w in enumerate(ws):
            f = fit1(xtr, ytr.astype(bool), w,
                     jax.random.PRNGKey(seed * 1000 + t))
            po[t] = np.asarray(trees.predict_proba(f, x[te]))[:, 1]
        rec = stats(tag, seed, po)
        rec.update(delta_vs_sk=round(rec["ens_f1"] - sk["ens_f1"], 4),
                   wall_s=round(time.time() - t0, 1))
        with open('/root/repo/_scratch/parity_diag.jsonl', 'a') as fd:
            fd.write(json.dumps(rec) + '\n')


for seed in range(int(sys.argv[1]) if len(sys.argv) > 1 else 2):
    run_seed(seed)
