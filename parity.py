"""Per-config F1 parity harness: our TPU sweep vs the pinned-stack pipeline.

BASELINE.md:28 requires per-config F1 within +/-0.01 of the sklearn stack on
the BASELINE.json probe configs. Both stacks carry irreducible RNG (sklearn
trees tie-break via MT19937 draws we cannot replicate; our PRNG is jax's), so
the comparison is between *seed-averaged* means — explicitly allowed by the
criterion ("ensemble configs may average seeds") — with the sample noise
reported alongside: for each config we print ours mean+/-sd (K_ours seeds),
sklearn mean+/-sd (K_sk seeds), the mean difference, and the standard error
of that difference. The +/-0.01 assertion is made at a size where SE < 0.01
(``--full``: N=4000+, 100 trees — run on the TPU); the small tier (pytest,
CPU) uses the same machinery as a regression guard with a tolerance scaled
to its own measured noise.

Reference semantics replicated on the sklearn side (experiment.py:446-490):
full-data preprocessing before CV, StratifiedKFold(10, shuffle, rs=0),
balance train folds only, pooled confusion -> P/R/F1. The resamplers use the
same numpy oracles as tests/ref_resamplers.py (imblearn 0.9 semantics;
imbalanced-learn is not installed here).

Usage:
    python parity.py            # small tier (CPU-friendly)
    python parity.py --full     # assertion tier (TPU; writes PARITY.json)
"""

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

# On the CPU backend, compare against sklearn in full precision — the same
# configuration the test suite's conftest pins (this jaxlib ignores the
# JAX_ENABLE_X64 env var, so it must be set via config, and before any jax
# array exists). Without this, standalone `python parity.py` ran the ours
# side in f32 while the suite ran it in f64, and the small tier's RF delta
# degraded past its tolerance in f32 only. The TPU tier stays f32 by
# design (no f64 hardware); PARITY.json records which backend ran.
# Gate on the env var, NOT jax.default_backend(): initializing the backend
# here would hang on a wedged axon tunnel (PROFILE.md round-3 finding).
if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax

    jax.config.update("jax_enable_x64", True)

# The three `scores` probe configs from BASELINE.json (the other two probes
# are the SHAP configs and the full-sweep run, covered elsewhere).
PROBE_CONFIGS = [
    ("NOD", "Flake16", "None", "None", "Decision Tree"),
    ("NOD", "Flake16", "Scaling", "SMOTE", "Random Forest"),
    ("OD", "Flake16", "PCA", "SMOTE Tomek", "Extra Trees"),
]


def _f1_from_conf(fp, fn, tp):
    prec = tp / (tp + fp) if tp + fp else None
    rec = tp / (tp + fn) if tp + fn else None
    if not prec or not rec:
        return 0.0
    return 2 * prec * rec / (prec + rec)


def _smote_np(x, y, rng):
    """imblearn-0.9-semantics SMOTE (numpy oracle, same draw structure)."""
    minority = 1 if (y == 1).sum() < (y == 0).sum() else 0
    x_min = x[y == minority]
    n_min, n_maj = len(x_min), int((y != minority).sum())
    n_new = n_maj - n_min
    if n_new > 0 and n_min > 1:
        d = ((x_min[:, None] - x_min[None]) ** 2).sum(-1)
        np.fill_diagonal(d, np.inf)
        k = min(5, n_min - 1)
        nn = np.argsort(d, axis=1)[:, :k]
        pick = rng.randint(0, n_min * k, n_new)
        base, col = pick // k, pick % k
        steps = rng.uniform(size=(n_new, 1))
        x_new = x_min[base] + steps * (x_min[nn[base, col]] - x_min[base])
        x = np.vstack([x, x_new])
        y = np.concatenate([y, np.full(n_new, bool(minority))])
    return x, y


def sklearn_config_f1(feats, labels, keys, *, n_trees, seed):
    """One seed of the reference pipeline for one config."""
    from sklearn.tree import DecisionTreeClassifier
    from sklearn.ensemble import (RandomForestClassifier,
                                  ExtraTreesClassifier)
    from sklearn.preprocessing import StandardScaler
    from sklearn.decomposition import PCA
    from sklearn.pipeline import Pipeline
    from sklearn.model_selection import StratifiedKFold
    from ref_resamplers import tomek_keep_ref, enn_keep_ref

    from flake16_framework_tpu import config as cfg

    fl_name, fs_name, prep_name, bal_name, model_name = keys
    fl = cfg.FLAKY_TYPES[fl_name]
    cols = list(cfg.FEATURE_SETS[fs_name])
    x = feats[:, cols].astype(np.float64)
    y = labels == fl
    if prep_name == "Scaling":
        x = StandardScaler().fit_transform(x)
    elif prep_name == "PCA":
        x = Pipeline([("s", StandardScaler()),
                      ("p", PCA(random_state=0))]).fit_transform(x)

    models = {
        "Decision Tree": lambda: DecisionTreeClassifier(random_state=seed),
        "Random Forest": lambda: RandomForestClassifier(
            random_state=seed, n_estimators=n_trees),
        "Extra Trees": lambda: ExtraTreesClassifier(
            random_state=seed, n_estimators=n_trees),
    }
    rng = np.random.RandomState(seed)

    def balance(xb, yb):
        if bal_name == "None":
            return xb, yb
        if bal_name == "Tomek Links":
            keep = tomek_keep_ref(xb, yb, False)
            return xb[keep], yb[keep]
        if bal_name == "ENN":
            keep = enn_keep_ref(xb, yb, False)
            return xb[keep], yb[keep]
        xb, yb = _smote_np(xb, yb, rng)
        if bal_name == "SMOTE Tomek":
            keep = tomek_keep_ref(xb, yb, True)
            return xb[keep], yb[keep]
        if bal_name == "SMOTE ENN":
            keep = enn_keep_ref(xb, yb, True)
            return xb[keep], yb[keep]
        return xb, yb

    fp = fn = tp = 0
    skf = StratifiedKFold(n_splits=10, shuffle=True, random_state=0)
    for tr, te in skf.split(x, y):
        xb, yb = balance(x[tr], y[tr])
        m = models[model_name]().fit(xb, yb)
        p = m.predict(x[te])
        fp += int((~y[te] & p).sum())
        fn += int((y[te] & ~p).sum())
        tp += int((y[te] & p).sum())
    return _f1_from_conf(fp, fn, tp)


def ours_config_f1s(feats, labels, pids, keys, *, n_trees, seeds,
                    grower=None):
    """Our jitted sweep for one config across seeds. One engine serves all
    seeds: the PRNG key is a traced argument of the compiled CV program
    (sweep.py run_config), so varying ``engine.seed`` hits the jit cache.
    ``grower`` selects the tier ("hist" production default / "exact"
    ladder-fallback tier — sweep.py _make_config_fns)."""
    from bench import dispatch_env as _dispatch_env
    from flake16_framework_tpu.parallel.sweep import SweepEngine

    names = [f"project{p:02d}" for p in range(int(pids.max()) + 1)]
    projects = np.array([names[p] for p in pids])
    dc, df = _dispatch_env()
    if grower == "exact" and dc is not None:
        # The exact grower is ~20x slower per tree than hist (gather-
        # bound): the bench's 25-tree dispatch default, sized for hist,
        # would put a multi-minute single dispatch on the TPU tunnel —
        # past the ~170 s fault envelope (PROFILE.md). 6 trees x 10 folds
        # per dispatch stays inside it at round-2 exact-grower rates.
        # 0 disables the clamp (same convention as the BENCH_* knobs).
        clamp = int(os.environ.get("PARITY_EXACT_DISPATCH", "6")) or None
        if clamp:
            dc = min(dc, clamp)
    engine = SweepEngine(
        feats, labels, projects, names, pids,
        tree_overrides={"Random Forest": n_trees, "Extra Trees": n_trees},
        grower=grower,
        # Bounded dispatches (same env knobs/defaults as bench.py): the
        # full tier runs 100-tree x 10-fold fits on the TPU tunnel, which
        # faults on multi-minute single dispatches (PROFILE.md).
        dispatch_trees=dc, dispatch_folds=df,
    )
    out = []
    for s in seeds:
        engine.seed = s
        out.append(_f1_from_conf(*engine.run_config(keys)[3][:3]))
    return out


def run_parity(*, n_tests, n_trees, k_ours, k_sk, data_seed=7,
               nod_bump=2.5, od_bump=1.8, noise_sigma=0.35, configs=None,
               sklearn_cache=None, exact_tier_models=(), k_exact=None,
               ours_exact_cache=None):
    """Seed-averaged F1 comparison. Returns a report dict per config.

    ``sklearn_cache``: optional path to a JSON of precomputed sklearn-side
    per-seed F1s ({"n_tests", "n_trees", "f1s": {"A/B/C/D/E": [...]}}) — the
    CPU side takes ~1 h single-core at full size, so it can be produced
    once and reused across ours-side (TPU) runs. Sizes must match.

    The CRITERION row is the shipped tier for every config — the tier
    that carries the bench number: hist for ensembles (RF/ET), exact for
    the single-tree DT (the sweep's tier rule). Rounds 3-6 could not say
    that for the ensembles: the histogram grower's raw bin-edge
    thresholds acted as a mild regularizer reading uniformly ABOVE
    sklearn (RF +0.0197, double the budget), so the criterion was judged
    on the exact grower with the hist delta published beside it. ISSUE
    9's exact-split refinement (hist node discovery, sklearn midpoint on
    the winning feature) closed that split for the ensembles; DT-on-hist
    still diverged (−0.066 small tier — no averaging to wash out
    bin-granular candidate ranking), so DT keeps the exact grower.

    ``exact_tier_models``: model names to ALSO measure on the exact
    (ladder-fallback) grower tier (sweep.py ``grower="exact"``),
    published in the row's ``exact_tier`` sub-dict — evidence the
    fallback tier still agrees, not the criterion. ``k_exact`` bounds
    its seed count (default ``k_ours``); ``ours_exact_cache`` is the
    ours-side twin of ``sklearn_cache`` (the exact grower costs
    ~1.5 h/seed on one CPU core, so wall-limited runs reuse seeds
    measured out-of-band — source and precision provenance recorded in
    the sub-dict)."""
    from flake16_framework_tpu.utils.synth import make_dataset

    params = dict(n_tests=n_tests, n_trees=n_trees, data_seed=data_seed,
                  nod_bump=nod_bump, od_bump=od_bump,
                  noise_sigma=noise_sigma)

    def load_cache(path, what):
        # a typo'd path must not silently fall back to a recompute, and
        # EVERY dataset parameter is validated (recorded at generation
        # time) — a cache from a different dataset must never validate.
        with open(path) as fd:
            c = json.load(fd)
        for name, val in params.items():
            assert name in c, (
                f"{what} cache lacks {name!r} — regenerate it (old caches "
                "without recorded dataset params are not trusted)"
            )
            assert c[name] == val, (
                f"{what} cache {name}={c[name]} != this run's {val}"
            )
        return c

    cache = load_cache(sklearn_cache, "sklearn") if sklearn_cache else None
    exact_cache = (load_cache(ours_exact_cache, "ours-exact")
                   if ours_exact_cache else None)
    run_prec = None
    if exact_cache is not None:
        # Precision is part of cache validity, not just provenance: an
        # f32-built cache consumed where the direct path would compute f64
        # reproduces the exact-grower's known f32 RF degradation (x64
        # header comment) under an f64-labeled record. The degradation
        # direction is an error; the reverse (f64 cache on an f32 run) is
        # strictly better data and only warned. Per-seed enforcement
        # happens at consumption (the top-level "precision" key is absent
        # from mixed-provenance caches — exact_seed_cache.py).
        import jax

        run_prec = ("f64" if jax.default_backend() == "cpu"
                    and jax.config.jax_enable_x64 else "f32")
        cache_prec = exact_cache.get("precision")
        if cache_prec is not None and cache_prec != run_prec:
            if run_prec == "f64" and cache_prec == "f32":
                raise AssertionError(
                    f"ours-exact cache is {cache_prec} but this run's "
                    f"direct path computes {run_prec} — rebuild the cache "
                    "(f32 exact-tier RF is the documented parity trap)")
            print(f"note: ours-exact cache precision {cache_prec} != "
                  f"run precision {run_prec} (higher-precision cache "
                  "consumed on a lower-precision run)", flush=True)
    feats, labels, pids = make_dataset(
        n_tests=n_tests, seed=data_seed, nod_bump=nod_bump, od_bump=od_bump,
        noise_sigma=noise_sigma,
    )
    report = {}
    for keys in (configs or PROBE_CONFIGS):
        deterministic = keys[4] == "Decision Tree" and "SMOTE" not in keys[3]
        ko = 1 if deterministic else k_ours
        # grower="hist" EXPLICITLY: this row is labeled as the production
        # tier below, so it must not silently inherit F16_ENSEMBLE_GROWER.
        # The sweep's tier rule applies the hist grower to ensembles only;
        # the single-tree DT config routes to the exact grower under this
        # same call (DT-on-hist diverged −0.066 on the small tier), so
        # every criterion row still measures the shipped fit path.
        ours = ours_config_f1s(feats, labels, pids, keys,
                               n_trees=n_trees, seeds=range(ko),
                               grower="hist")
        if cache is not None:
            sk = cache["f1s"]["/".join(keys)]
            assert len(sk) >= max(k_sk, 2), (
                f"cache has {len(sk)} seeds for {keys}, need {k_sk}"
            )
            # Keep >= 2 seeds even if k_sk == 1: std(ddof=1) of one value
            # is nan and would poison se_delta.
            sk = sk[:max(k_sk, 2)]
        else:
            sk = [sklearn_config_f1(feats, labels, keys,
                                    n_trees=n_trees, seed=s)
                  for s in range(k_sk)]
        o, s = np.array(ours), np.array(sk)

        def side(o_arr):
            se = float(np.sqrt(
                (o_arr.std(ddof=1) ** 2 / len(o_arr) if len(o_arr) > 1
                 else 0.0)
                + s.std(ddof=1) ** 2 / len(s)
            ))
            return {
                "ours_mean": round(float(o_arr.mean()), 4),
                "ours_sd": round(float(o_arr.std()), 4),
                "ours_k": len(o_arr),
                "sklearn_mean": round(float(s.mean()), 4),
                "sklearn_sd": round(float(s.std()), 4),
                "sklearn_k": len(s),
                "delta": round(float(o_arr.mean() - s.mean()), 4),
                "se_delta": round(se, 4),
            }

        entry = side(o)
        # the tier that measured this row — hist for ensembles; the
        # single-tree DT stays on the exact grower (sweep tier rule)
        entry["grower"] = ("exact" if keys[4] == "Decision Tree"
                           else "hist")
        if keys[4] in exact_tier_models and keys[4] != "Decision Tree":
            kx = k_exact or k_ours
            ox, src = None, "computed"
            if exact_cache is not None:
                got = exact_cache["f1s"].get("/".join(keys), [])
                # an under-seeded cache must fail loudly, not silently
                # judge the ±0.01 assertion on fewer seeds than configured
                assert len(got) >= kx, (
                    f"ours-exact cache has {len(got)} seeds for {keys}, "
                    f"need {kx} (lower PARITY_K_EXACT or extend the cache)"
                )
                # per-seed precision check on the CONSUMED slice: a
                # mixed-provenance cache (no top-level "precision") must
                # not smuggle f32 seeds into an f64 run — and a cache with
                # NO per-seed provenance at all is rejected on f64 runs
                # unless its uniform precision says f64 (same distrust
                # principle as load_cache's params check)
                seed_prov = exact_cache.get(
                    "seed_provenance", {}).get("/".join(keys), [])
                if run_prec == "f64" and \
                        exact_cache.get("precision") != "f64":
                    assert len(seed_prov) >= kx, (
                        f"ours-exact cache for {keys} lacks per-seed "
                        "precision provenance and is not uniformly f64 — "
                        "cannot rule out f32 seeds on an f64 run; "
                        "regenerate with tools/exact_seed_cache.py")
                    bad = [p for p in seed_prov[:kx]
                           if p.get("precision") != "f64"]
                    assert not bad, (
                        f"ours-exact cache seeds {[p['seed'] for p in bad]}"
                        f" for {keys} are not f64 but this run computes "
                        "f64 — rebuild those seeds")
                ox = np.array(got[:kx])
                src = "cache:" + os.path.basename(ours_exact_cache) + (
                    f" ({exact_cache['precision']})"
                    if "precision" in exact_cache else "")
            if ox is None:
                ox = np.array(ours_config_f1s(
                    feats, labels, pids, keys, n_trees=n_trees,
                    seeds=range(kx), grower="exact",
                ))
            exact_entry = side(ox)
            exact_entry["grower"] = "exact"
            exact_entry["ours_source"] = src
            # the REQUESTED seed count, so a record judged on an
            # operator-lowered PARITY_K_EXACT is visibly under-default
            exact_entry["k_exact_requested"] = kx
            # Criterion row = the shipped (production/bench) tier — hist
            # for ensembles since the ISSUE-9 refinement; the exact
            # grower is the ensembles' ladder-fallback tier and its
            # measurement, when requested, is published BESIDE the
            # criterion, not as it.
            entry["exact_tier"] = exact_entry
        report["/".join(keys)] = entry
        print(json.dumps({keys[4]: entry}), flush=True)
    return report


def gen_cache(out_path, *, n_tests=4000, n_trees=100, k=6, data_seed=7,
              nod_bump=2.5, od_bump=1.8, noise_sigma=0.35):
    """Precompute the sklearn side of the full tier (~1 h single-core) and
    write it with EVERY dataset parameter recorded, so ``run_parity``'s
    cache-compat check never needs a defaults fallback."""
    from flake16_framework_tpu.utils.synth import make_dataset

    feats, labels, _ = make_dataset(
        n_tests=n_tests, seed=data_seed, nod_bump=nod_bump, od_bump=od_bump,
        noise_sigma=noise_sigma,
    )
    f1s = {}
    for keys in PROBE_CONFIGS:
        f1s["/".join(keys)] = [
            sklearn_config_f1(feats, labels, keys, n_trees=n_trees, seed=s)
            for s in range(k)
        ]
        print(json.dumps({keys[4]: f1s["/".join(keys)]}), flush=True)
    out = {"n_tests": n_tests, "n_trees": n_trees, "k": k,
           "data_seed": data_seed, "nod_bump": nod_bump, "od_bump": od_bump,
           "noise_sigma": noise_sigma, "f1s": f1s}
    with open(out_path, "w") as fd:
        json.dump(out, fd, indent=2)
    return out


def main():
    full = "--full" in sys.argv
    if "--gen-cache" in sys.argv:
        out_path = sys.argv[sys.argv.index("--gen-cache") + 1]
        gen_cache(out_path)
        return
    if full:
        # The criterion tier is hist — the production/bench tier — for
        # every config (run_parity docstring). The exact fallback tier is
        # measured beside it only when requested: PARITY_EXACT_TIER_MODELS
        # ("Random Forest,Extra Trees"-style) names the rows, and the
        # seeds come from PARITY_OURS_EXACT_CACHE when present (the exact
        # grower costs ~40+ min/seed on one CPU core at full size;
        # PARITY_K_EXACT trades seeds for completion).
        exact_models = tuple(
            m.strip() for m in
            os.environ.get("PARITY_EXACT_TIER_MODELS", "").split(",")
            if m.strip())
        rep = run_parity(
            n_tests=4000, n_trees=100, k_ours=6, k_sk=6,
            sklearn_cache=os.environ.get("PARITY_SKLEARN_CACHE"),
            exact_tier_models=exact_models,
            k_exact=int(os.environ.get("PARITY_K_EXACT", "6")),
            ours_exact_cache=os.environ.get("PARITY_OURS_EXACT_CACHE"),
        )
        import jax

        tol = 0.01
        k_exact = int(os.environ.get("PARITY_K_EXACT", "6"))
        out = {"tier": "full", "n_tests": 4000, "n_trees": 100,
               "tolerance": tol, "configs": rep,
               # provenance: results are backend-independent by design
               # (bit-pinned hist formulations, backend-deterministic PRNG)
               # but the record must say which backend ran the ours side
               "ours_backend": jax.default_backend(),
               # Self-describing tier flags (round-4 advisor, flipped by
               # ISSUE 9): top-level ok judges the CRITERION tier, which
               # since the refinement IS the shipped production/bench
               # tier — hist for ensembles, exact for single-tree DT
               # (per-row "grower" says which); any measured
               # exact-fallback rows are judged separately here so a
               # machine consumer reading only ok+tolerance cannot
               # mistake one for the other.
               "criterion_tier": "hist-ensembles",
               "exact_tier_models": list(exact_models),
               "exact_tier_within_tol": all(
                   abs(v["exact_tier"]["delta"]) <= tol
                   for v in rep.values() if "exact_tier" in v),
               "k_exact": k_exact, "k_exact_default": 6,
               "ok": all(abs(v["delta"]) <= tol for v in rep.values())}
        # Atomic replace: a kill mid-dump must never corrupt an existing
        # green record.
        path = os.path.join(REPO, "PARITY.json")
        with open(path + ".tmp", "w") as fd:
            json.dump(out, fd, indent=2)
        os.replace(path + ".tmp", path)
        print(json.dumps({"parity_ok": out["ok"], "tolerance": tol}))
        if not out["ok"]:
            sys.exit(1)
    else:
        run_small_tier()
        print(json.dumps({"parity_small_ok": True}))


def run_small_tier():
    """The CPU regression tier (shared by ``python parity.py`` and pytest):
    same machinery as --full, sized for CI, tolerance scaled to its own
    measured noise (at this size sklearn's seed sd alone exceeds 0.01).
    The criterion rows run the shipped tier like --full (hist ensembles,
    exact single-tree DT); RF ALSO measures the exact fallback tier so
    that path (exact-grower
    ensembles through the chunked sweep) stays exercised end-to-end on
    every CI run, not first on the TPU."""
    rep = run_parity(n_tests=800, n_trees=16, k_ours=2, k_sk=4,
                     exact_tier_models=("Random Forest",))
    for name, v in rep.items():
        tol = max(0.05, 3 * v["se_delta"])
        assert abs(v["delta"]) <= tol, (name, v)
        if "exact_tier" in v:
            d = v["exact_tier"]
            assert abs(d["delta"]) <= max(0.05, 3 * d["se_delta"]), (name, d)
    return rep


if __name__ == "__main__":
    main()
