# Experiment container image for the TPU-native Flake16 framework (L1,
# SURVEY.md §1): builds the `flake16framework` image that
# runner/containers.docker_command launches 26x5,001 times, each container
# running
#
#     python3 -m flake16_framework_tpu container <name> <commands...>
#
# Layout inside the image (constants.py): the framework source tree at
# /home/user/framework (installed with --no-deps into every subject venv —
# it carries both pytest plugins via pyproject entry points), subject venvs +
# checkouts under /home/user/subjects, collected artifacts bind-mounted at
# /home/user/data.
#
# Per-subject dependency pins (subjects/<proj>/requirements.txt — a pip
# freeze of the resolved env at the subject's pinned SHA): the repo vendors
# the study's 26 freezes, and the COPY below places them straight into the
# image's SUBJECTS_DIR, so setup runs pinned by default; replace a
# subject's file before building (or in the work dir at run time — it
# wins) to re-freeze, and setup falls back to unpinned resolution only
# when a subject has no pins at all (runner/containers.provision_subject;
# caveat: the vendored freezes resolved on the study's py3.8 image — see
# subjects/README.md).
#
# Base: noble (Python 3.12). The testinspect plugin traces coverage via
# sys.monitoring (PEP 669, 3.12+) instead of bundling coverage.py into every
# subject venv, so subject venvs need a 3.12 interpreter; per-subject pins
# must be resolved against it (the original study's focal-era pins predate
# this and would need re-resolving regardless of framework).

FROM ubuntu:noble

ARG DEBIAN_FRONTEND=noninteractive

# Toolchain + native headers the 26 subjects' builds need (subjects.txt):
# scientific stack (BLAS/LAPACK), imaging (Pillow/scikit-image/fonttools),
# crypto + ledger (electrum), DB clients (airflow/celery), JDK (conan tests).
RUN apt-get update && apt-get install -y --no-install-recommends \
    build-essential \
    cmake \
    git \
    pkg-config \
    default-jdk \
    python3 \
    python3-dev \
    python3-pip \
    python3-tk \
    virtualenv \
    libcurl4-openssl-dev \
    libssl-dev \
    libkrb5-dev \
    libldap2-dev \
    libsasl2-dev \
    libfreetype6-dev \
    libfribidi-dev \
    libharfbuzz-dev \
    libjpeg-turbo8-dev \
    liblcms2-dev \
    libopenjp2-7-dev \
    libtiff-dev \
    libwebp-dev \
    libxcb1-dev \
    tcl8.6-dev \
    tk8.6-dev \
    zlib1g-dev \
    liblapack-dev \
    libopenblas-dev \
    libmysqlclient-dev \
    libpq-dev \
    unixodbc-dev \
    libsecp256k1-dev \
    libsndfile1-dev \
    && rm -rf /var/lib/apt/lists/*

RUN useradd -ms /bin/bash user

USER user

WORKDIR /home/user

# The framework source tree (includes the packaged subjects.txt registry and
# both pytest plugins). Installed editable-style into subject venvs by setup.
COPY --chown=user pyproject.toml framework/
COPY --chown=user flake16_framework_tpu framework/flake16_framework_tpu

# Optional per-subject pins (see header). The directory may be empty.
COPY --chown=user subjects subjects

# The host CLI inside the image: provision all 26 subject venvs. The
# framework itself is importable straight from the source tree (the L1/L2
# verbs are stdlib-only; the jax stack is only imported by scores/shap,
# which run on the TPU host, not in containers).
ENV PYTHONPATH=/home/user/framework
RUN python3 -m flake16_framework_tpu setup
